"""Offline archive analysis: per-technique attribution + convergence.

The reference answers "which technique found the best, and how fast did
each converge" by post-hoc SQL over its results DBs
(`/root/reference/python/uptune/opentuner/utils/stats.py`, 478 LoC of
per-technique convergence CSV extraction + `stats_matplotlib.py`
rendering, fed by the requestor column of every Result,
`resultsdb/models.py:234-300`).  Our jsonl trial archive carries the
same attribution (`tech` per row, driver/driver.py _log_trial), so the
whole analysis is one pass over the file.

CLI:  ut-stats ut.archive.jsonl [--csv out.csv] [--plot out.png]
      ut-stats ut.archive.jsonl --follow     # live during-run view

`--follow` replaces the reference's decouple-mode runtime matplotlib
dashboard (src/async_task_scheduler.py:148-209 blitting QoR curves): it
tails the archive as the controller appends trials and re-renders
best-so-far + per-technique attribution in place, working over ssh where
a GUI dashboard cannot.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from typing import Any, Dict, List, Optional

Row = Dict[str, Any]


def load_archive(path: str) -> List[Row]:
    """Read archive rows (skipping the space-signature header and any
    torn tail line)."""
    rows: List[Row] = []
    bad_line = None   # one-line lookbehind: junk is only OK at EOF
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            if bad_line is not None:
                # the junk was mid-file, not a torn tail: skip THAT line
                # only — dropping the rest would silently falsify
                # attribution counts
                print(f"ut-stats: skipping corrupt line {bad_line} of "
                      f"{path}", file=sys.stderr)
                bad_line = None
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_line = lineno
                continue
            if "space_sig" in rec:
                continue
            rows.append(rec)
    return rows


def technique_report(rows: List[Row], sense: str = "min"
                     ) -> Dict[str, Dict[str, Any]]:
    """Per-technique attribution: evals, failures, best QoR, new-best
    count, eval index of the global best, mean eval time."""
    sign = 1.0 if sense == "min" else -1.0
    best_val = math.inf
    best_tech: Optional[str] = None
    best_idx: Optional[int] = None
    out: Dict[str, Dict[str, Any]] = {}
    for i, r in enumerate(rows):
        tech = r.get("tech", "?")
        st = out.setdefault(tech, {
            "evals": 0, "failures": 0, "new_bests": 0,
            "best_qor": math.inf, "time_sum": 0.0,
            "first_eval": i, "global_best_at": None})
        st["evals"] += 1
        st["time_sum"] += float(r.get("time", 0.0))
        q = float(r["qor"])
        eng = sign * q
        if not math.isfinite(eng):
            st["failures"] += 1
            continue
        st["best_qor"] = min(st["best_qor"], eng)
        if r.get("best"):
            st["new_bests"] += 1
        if eng < best_val:
            best_val, best_tech, best_idx = eng, tech, i
    for tech, st in out.items():
        st["mean_time"] = (st["time_sum"] / st["evals"]
                           if st["evals"] else 0.0)
        del st["time_sum"]
        st["found_global_best"] = tech == best_tech
        if tech == best_tech:
            st["global_best_at"] = best_idx
        if math.isfinite(st["best_qor"]):
            st["best_qor"] = sign * st["best_qor"]   # user orientation
        else:
            st["best_qor"] = None
    return out


def convergence(rows: List[Row], sense: str = "min"
                ) -> Dict[str, List[List[float]]]:
    """Per-technique best-so-far curve: [eval_index, tech_best] pairs at
    each improvement (the per-technique convergence CSVs the reference
    extracts, opentuner/utils/stats.py)."""
    sign = 1.0 if sense == "min" else -1.0
    cur: Dict[str, float] = {}
    out: Dict[str, List[List[float]]] = {}
    for i, r in enumerate(rows):
        tech = r.get("tech", "?")
        q = sign * float(r["qor"])
        if not math.isfinite(q):
            continue
        if q < cur.get(tech, math.inf):
            cur[tech] = q
            out.setdefault(tech, []).append([i, sign * q])
    return out


def render_table(report: Dict[str, Dict[str, Any]]) -> str:
    cols = ("technique", "evals", "failures", "new_bests", "best_qor",
            "mean_time_s", "found_best")
    lines = ["  ".join(f"{c:>14}" for c in cols)]
    order = sorted(report, key=lambda t: -report[t]["evals"])
    for tech in order:
        st = report[tech]
        bq = ("-" if st["best_qor"] is None
              else f"{st['best_qor']:.6g}")
        row = (tech, st["evals"], st["failures"], st["new_bests"], bq,
               f"{st['mean_time']:.3f}",
               "*" if st["found_global_best"] else "")
        lines.append("  ".join(f"{str(v):>14}" for v in row))
    return "\n".join(lines)


def write_csv(rows: List[Row], path: str, sense: str = "min") -> None:
    conv = convergence(rows, sense)
    with open(path, "w") as f:
        f.write("technique,eval_index,best_so_far\n")
        for tech in sorted(conv):
            for i, v in conv[tech]:
                f.write(f"{tech},{int(i)},{v}\n")


def plot(rows: List[Row], path: str, sense: str = "min") -> bool:
    """Best-so-far-per-technique step plot; returns False when
    matplotlib is unavailable (optional dependency, like the
    reference's stats_matplotlib)."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return False
    conv = convergence(rows, sense)
    fig, ax = plt.subplots(figsize=(8, 5))
    for tech in sorted(conv):
        pts = conv[tech]
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        ax.step(xs, ys, where="post", label=tech)
    ax.set_xlabel("evaluation")
    ax.set_ylabel("best QoR so far")
    ax.legend(fontsize=7)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return True


def compare_convergence(rowsets: List[List[Row]], sense: str = "min",
                        points: int = 200
                        ) -> Dict[str, List[List[float]]]:
    """Cross-RUN technique comparison (the reference's
    stats_matplotlib.py:1-298 median-best-vs-evals figures): for each
    technique, the MEDIAN best-so-far across archives, sampled on a
    shared eval-index grid.  An archive contributes to a technique's
    median only from that technique's first finite eval onward."""
    sign = 1.0 if sense == "min" else -1.0
    n = max((len(r) for r in rowsets), default=0)
    if not n:
        return {}
    step = max(1, n // max(points, 1))
    grid = list(range(0, n, step))
    if grid[-1] != n - 1:
        grid.append(n - 1)

    # per archive, per technique: best-so-far at each grid point; a run
    # that ENDS keeps contributing its final best-so-far to every later
    # grid point (carry-forward) — dropping it would make the median
    # "best-so-far" JUMP when a short (target-hit) run finishes, and a
    # regressing best-so-far statistic is impossible in reality
    per_tech: Dict[str, List[List[Optional[float]]]] = {}
    for rows in rowsets:
        cur: Dict[str, float] = {}
        sampled: Dict[str, List[Optional[float]]] = {}
        gi = 0
        for i, r in enumerate(rows):
            q = sign * float(r["qor"])
            tech = r.get("tech", "?")
            if math.isfinite(q) and q < cur.get(tech, math.inf):
                cur[tech] = q
            while gi < len(grid) and grid[gi] <= i:
                for t, v in cur.items():
                    col = sampled.setdefault(t, [None] * len(grid))
                    col[gi] = v
                gi += 1
        for t, v in cur.items():          # carry past the run's end
            col = sampled.setdefault(t, [None] * len(grid))
            for g in range(gi, len(grid)):
                col[g] = v
        for t, col in sampled.items():
            per_tech.setdefault(t, []).append(col)

    out: Dict[str, List[List[float]]] = {}
    for tech, cols in per_tech.items():
        pts = []
        for gi, idx in enumerate(grid):
            vals = sorted(c[gi] for c in cols if c[gi] is not None)
            if not vals:
                continue
            mid = len(vals) // 2
            med = (vals[mid] if len(vals) % 2
                   else 0.5 * (vals[mid - 1] + vals[mid]))
            pts.append([idx, sign * med])
        if pts:
            out[tech] = pts
    return out


def plot_compare(rowsets: List[List[Row]], labels: List[str],
                 path: str, sense: str = "min",
                 conv: Optional[Dict[str, List[List[float]]]] = None
                 ) -> bool:
    """One line per technique: median best-so-far across the archives
    (stats_matplotlib's cross-run comparison figure).  Pass `conv` to
    reuse an already-computed compare_convergence fold."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return False
    if conv is None:
        conv = compare_convergence(rowsets, sense)
    fig, ax = plt.subplots(figsize=(8, 5))
    for tech in sorted(conv):
        xs = [p[0] for p in conv[tech]]
        ys = [p[1] for p in conv[tech]]
        ax.step(xs, ys, where="post", label=tech)
    ax.set_xlabel("evaluation")
    ax.set_ylabel(f"median best QoR so far ({len(rowsets)} runs)")
    ax.set_title(", ".join(labels[:4]) + ("…" if len(labels) > 4 else ""))
    ax.legend(fontsize=7)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return True


def render_compare_table(rowsets: List[List[Row]], labels: List[str],
                         sense: str = "min") -> str:
    """Per-technique cross-archive summary: in how many runs it appears,
    total evals, and its best QoR over all runs."""
    sign = 1.0 if sense == "min" else -1.0
    agg: Dict[str, Dict[str, Any]] = {}
    for rows in rowsets:
        seen = set()
        for r in rows:
            tech = r.get("tech", "?")
            st = agg.setdefault(tech, {"runs": 0, "evals": 0,
                                       "best": math.inf})
            st["evals"] += 1
            if tech not in seen:
                st["runs"] += 1
                seen.add(tech)
            q = sign * float(r["qor"])
            if math.isfinite(q):
                st["best"] = min(st["best"], q)
    lines = [f"cross-run comparison over {len(rowsets)} archives: "
             + ", ".join(labels)]
    lines.append("  ".join(f"{c:>14}" for c in
                           ("technique", "runs", "evals", "best_qor")))
    for tech in sorted(agg, key=lambda t: -agg[t]["evals"]):
        st = agg[tech]
        bq = ("-" if not math.isfinite(st["best"])
              else f"{sign * st['best']:.6g}")
        lines.append("  ".join(f"{str(v):>14}" for v in
                               (tech, st["runs"], st["evals"], bq)))
    return "\n".join(lines)


class ArchiveTail:
    """Incremental archive reader for --follow: returns newly appended
    complete rows per poll, surviving slow writers (partial trailing
    lines are buffered, not dropped) and archive rotation (the driver
    rotates a space-mismatched archive on resume — detected by the file
    shrinking, which resets the cursor)."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.partial = b""

    def read_new(self) -> List[Row]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self.offset:            # rotated/truncated: start over
            self.offset = 0
            self.partial = b""
        if size == self.offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            chunk = f.read()
            self.offset = f.tell()
        data = self.partial + chunk
        lines = data.split(b"\n")
        self.partial = lines.pop()        # b"" when chunk ended in \n
        rows: List[Row] = []
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if "space_sig" not in rec:
                rows.append(rec)
        return rows


def compact_archive(path: str) -> Dict[str, int]:
    """Rewrite a trial archive keeping the signature header and the
    FIRST row of every distinct configuration (the reference ships
    `compactdb.py` for the same jsonl-grows-unboundedly problem in its
    SQL results DB).  Order is preserved, so resume replay — which
    inserts each config into the dedup history once and serves later
    duplicates from it — reconstructs the identical dedup history, best
    and per-config results; only the redundant duplicate rows (in-batch
    dup serves, re-proposals) are dropped.  The drop COUNT is recorded
    in the signature header (`compacted_rows`, cumulative) so a resumed
    Tuner's evals/told budget accounting does not shrink — without it a
    `run(test_limit=N)` after compaction would re-spend the dropped
    rows' budget in real evaluations.  (The best-so-far trace does
    coarsen to unique configs; that is the information compaction
    discards.)  Atomic: the original is replaced only after the
    compacted file is fully written, preserving the original file mode.

    OFFLINE ONLY: a driver holding the archive open in append mode would
    keep writing to the old (replaced, unlinked) inode — every trial
    after the swap would silently vanish.  The size is re-checked just
    before the swap and the compaction ABORTS if the archive grew, so
    running `--compact` against a live tuning run fails loudly instead
    of eating rows (racy in principle, reliable for the steady append
    stream a live run produces)."""
    import stat as stat_mod
    import tempfile

    before = after = 0
    seen = set()
    size0 = os.path.getsize(path)
    mode0 = stat_mod.S_IMODE(os.stat(path).st_mode)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".compact")
    try:
        # two passes: the header must carry the cumulative drop count,
        # which is only known after the dedup scan
        body = []
        header = None
        with open(path) as f:
            for line in f:
                sline = line.strip()
                if not sline:
                    continue
                try:
                    rec = json.loads(sline)
                except json.JSONDecodeError:
                    continue          # torn tail / corruption: drop
                if "space_sig" in rec:
                    if header is None:
                        header = rec
                    continue
                before += 1
                key = json.dumps([rec.get("u"), rec.get("perms")])
                if key in seen:
                    continue
                seen.add(key)
                after += 1
                body.append(sline)
        with os.fdopen(fd, "w") as out:
            if header is not None:
                header["compacted_rows"] = (
                    int(header.get("compacted_rows", 0))
                    + (before - after))
                out.write(json.dumps(header) + "\n")
            for sline in body:
                out.write(sline + "\n")
        # mkstemp creates 0600; keep the archive's own permissions so
        # other readers (a dashboard tailing --follow) don't lose access
        os.chmod(tmp, mode0)
        if os.path.getsize(path) != size0:
            raise RuntimeError(
                f"{path} grew while compacting — a tuner appears to be "
                "writing to it; compact archives only after the run "
                "has finished")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return {"rows_before": before, "rows_after": after}


class FollowAccumulator:
    """Incremental fold of the --follow view: O(new rows) per poll
    instead of re-reducing the whole archive every 2 s tick (VERDICT r3
    weak #6 — the full recompute turns sluggish at 10^5 rows).  Carries
    exactly the state technique_report() derives: per-technique counters
    plus the global best attribution."""

    def __init__(self, sense: str = "min"):
        self.sign = 1.0 if sense == "min" else -1.0
        self.n = 0
        self.failures = 0
        self.best_val = math.inf        # engine orientation
        self.best_tech: Optional[str] = None
        self.best_idx: Optional[int] = None
        self.last_best_i: Optional[int] = None
        self.report: Dict[str, Dict[str, Any]] = {}

    def update(self, new_rows: List[Row]) -> None:
        for r in new_rows:
            i = self.n
            self.n += 1
            tech = r.get("tech", "?")
            st = self.report.setdefault(tech, {
                "evals": 0, "failures": 0, "new_bests": 0,
                "best_qor": math.inf, "time_sum": 0.0,
                "first_eval": i, "global_best_at": None})
            st["evals"] += 1
            st["time_sum"] += float(r.get("time", 0.0))
            q = self.sign * float(r["qor"])
            if not math.isfinite(q):
                st["failures"] += 1
                self.failures += 1
                continue
            st["best_qor"] = min(st["best_qor"], q)
            if r.get("best"):
                st["new_bests"] += 1
                self.last_best_i = i
            if q < self.best_val:
                self.best_val, self.best_tech, self.best_idx = q, tech, i

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Materialize a technique_report()-shaped dict (cheap: one pass
        over the technique SUMMARIES, not the rows)."""
        out = {}
        for tech, st in self.report.items():
            d = dict(st)
            d["mean_time"] = (d.pop("time_sum") / d["evals"]
                              if d["evals"] else 0.0)
            d["found_global_best"] = tech == self.best_tech
            d["global_best_at"] = (self.best_idx
                                   if tech == self.best_tech else None)
            d["best_qor"] = (self.sign * d["best_qor"]
                             if math.isfinite(d["best_qor"]) else None)
            out[tech] = d
        return out

    def render(self, started: float) -> str:
        best = (self.sign * self.best_val
                if math.isfinite(self.best_val) else None)
        head = [
            f"ut-stats --follow   evals={self.n} "
            f"failures={self.failures} "
            f"best={'-' if best is None else f'{best:.6g}'} "
            f"last_improvement=@"
            f"{'-' if self.last_best_i is None else self.last_best_i} "
            f"uptime={time.time() - started:.0f}s",
            "",
        ]
        return "\n".join(head) + render_table(self.snapshot())


def follow(path: str, sense: str = "min", interval: float = 2.0,
           max_polls: Optional[int] = None) -> int:
    """Tail the archive and re-render the live view every `interval`
    seconds until interrupted (`max_polls` bounds the loop for tests)."""
    tail = ArchiveTail(path)
    acc = FollowAccumulator(sense)
    started = time.time()
    polls = 0
    dirty = True
    try:
        while max_polls is None or polls < max_polls:
            polls += 1
            new = tail.read_new()
            if new:
                acc.update(new)
                dirty = True
            if dirty:
                view = acc.render(started)
                if sys.stdout.isatty():
                    sys.stdout.write("\x1b[2J\x1b[H" + view + "\n")
                else:
                    sys.stdout.write(view + "\n")
                sys.stdout.flush()
                dirty = False
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ut-stats",
        description="per-technique attribution report from a jsonl "
                    "trial archive")
    ap.add_argument("archive", nargs="+",
                    help="one archive: attribution report; several: "
                         "cross-run technique comparison (median "
                         "best-so-far per technique across runs)")
    ap.add_argument("--sense", choices=("min", "max"), default="min")
    ap.add_argument("--csv", help="write per-technique convergence CSV")
    ap.add_argument("--plot", help="write convergence plot PNG")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON")
    ap.add_argument("--follow", action="store_true",
                    help="live during-run view: tail the archive and "
                         "re-render best-so-far + attribution")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--follow poll interval in seconds")
    ap.add_argument("--compact", action="store_true",
                    help="rewrite the archive dropping duplicate-config "
                         "rows (order-preserving, resume-safe; the "
                         "compactdb.py equivalent)")
    args = ap.parse_args(argv)
    if args.compact:
        for p in args.archive:
            st = compact_archive(p)
            print(f"ut-stats: compacted {p}: {st['rows_before']} -> "
                  f"{st['rows_after']} rows")
        return 0
    if args.follow:
        if len(args.archive) > 1:
            print("ut-stats: --follow takes exactly one archive",
                  file=sys.stderr)
            return 2
        return follow(args.archive[0], args.sense, args.interval)
    if len(args.archive) > 1:
        # cross-run comparison mode (stats_matplotlib.py equivalent)
        rowsets, labels = [], []
        for p in args.archive:
            rs = load_archive(p)
            if rs:
                rowsets.append(rs)
                labels.append(os.path.basename(p))
        if not rowsets:
            print("ut-stats: all archives empty", file=sys.stderr)
            return 1
        # one fold serves --json, --csv and the plot (the fold is the
        # O(runs × rows) part; at 10^5-row archives it must not repeat)
        conv = (compare_convergence(rowsets, args.sense)
                if (args.json or args.csv or args.plot) else None)
        if args.json:
            print(json.dumps(conv, indent=1))
        else:
            print(render_compare_table(rowsets, labels, args.sense))
        if args.csv:
            with open(args.csv, "w") as f:
                f.write("technique,eval_index,median_best_so_far\n")
                for tech in sorted(conv):
                    for i, v in conv[tech]:
                        f.write(f"{tech},{int(i)},{v}\n")
        if args.plot and not plot_compare(rowsets, labels, args.plot,
                                          args.sense, conv=conv):
            print("ut-stats: matplotlib unavailable; no plot",
                  file=sys.stderr)
        return 0
    rows = load_archive(args.archive[0])
    if not rows:
        print("ut-stats: empty archive", file=sys.stderr)
        return 1
    report = technique_report(rows, args.sense)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_table(report))
    if args.csv:
        write_csv(rows, args.csv, args.sense)
    if args.plot and not plot(rows, args.plot, args.sense):
        print("ut-stats: matplotlib unavailable; no plot",
              file=sys.stderr)
    return 0


def _entry() -> int:
    try:
        return main()
    except BrokenPipeError:     # `ut-stats ... | head` is normal usage
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(_entry())
