"""Child-process import seam: make `import uptune_tpu` work in spawned
subprocesses (analysis runs, sandboxed eval workers, --num-hosts fleet
members) even from a plain checkout with no `pip install -e .`.

For an installed package the computed directory is site-packages —
already importable, so the entry is inert.
"""
from __future__ import annotations

import os
from typing import Optional


def pkg_parent_dir() -> str:
    """Directory CONTAINING the uptune_tpu package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def child_pythonpath(existing: Optional[str] = None) -> str:
    """PYTHONPATH value for a child process: the package parent dir
    prepended to `existing` (defaults to the current environment's)."""
    pp = (os.environ.get("PYTHONPATH", "")
          if existing is None else existing)
    root = pkg_parent_dir()
    if root in pp.split(os.pathsep):
        return pp
    return root + (os.pathsep + pp if pp else "")
