"""Force a virtual multi-device CPU platform, safely.

The machine environment registers an experimental `axon` TPU-tunnel
backend whose PJRT client dials the tunnel during backends()
initialization — even under JAX_PLATFORMS=cpu — and hangs the process if
the tunnel is wedged (observed: 300 s+).  Every CPU-only entry point
(tests, dryruns, bench fallback) must therefore (a) select the cpu
platform, (b) size the virtual device count, and (c) drop the axon
backend factory BEFORE any JAX backend initializes.

This module is importable without importing jax at module scope, so it is
safe to call from conftest-style preambles.  Must be called before the
first jax backend initialization to take full effect.
"""
from __future__ import annotations

import os
import re

_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=(\d+)")


def force_cpu(n_devices: int = 8, compile_cache: bool = True) -> None:
    """Select the CPU platform with >= n_devices virtual devices and
    drop the axon TPU-tunnel backend factory.

    Also enables the persistent XLA compilation cache (machine-local,
    `.xla_cache/` at the repo root, override with UT_COMPILE_CACHE_DIR,
    disable with UT_NO_COMPILE_CACHE=1): the test suite and CPU drives
    re-jit the same engine/driver programs every process, and the disk
    cache turns those 7-15s compiles into ~1s loads on every run after
    the first (measured 6.8s -> 1.1s for the fused engine program)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = _COUNT_RE.search(flags)
    if m is None:
        flags = (flags +
                 f" --xla_force_host_platform_device_count={n_devices}")
    elif int(m.group(1)) < n_devices:
        flags = _COUNT_RE.sub(
            f"--xla_force_host_platform_device_count={n_devices}", flags)
    os.environ["XLA_FLAGS"] = flags.strip()

    import jax

    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass  # private API moved: the env vars above still select cpu
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)
    if compile_cache:
        enable_compile_cache()


def default_cache_dir() -> str:
    """Default persistent-cache location: repo checkout -> .xla_cache at
    the root; installed package (three dirnames land in site-packages'
    parent) -> a user cache dir, never inside the venv lib tree."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.exists(os.path.join(root, "pyproject.toml")):
        return os.path.join(root, ".xla_cache")
    return os.path.join(os.path.expanduser("~"), ".cache", "uptune_tpu",
                        "xla")


def enable_compile_cache(cache_dir=None, subdir=None):
    """Point JAX's persistent compilation cache at `cache_dir` (resolved
    via UT_COMPILE_CACHE_DIR then default_cache_dir() when None), with an
    optional `subdir` component (the controller keys it by the space
    signature so each tuned program's executables live together and can
    be evicted independently).  Returns the directory in effect, or None
    when disabled (UT_NO_COMPILE_CACHE=1) or unsupported by this jax.

    The cache keys on the compiled computation itself, so a stale entry
    can never be served for a changed program; the test suite and CPU
    drives re-jit the same engine/driver programs every process, and the
    disk cache turns those 7-15s compiles into ~1s loads on every run
    after the first (measured 6.8s -> 1.1s for the fused engine
    program)."""
    if os.environ.get("UT_NO_COMPILE_CACHE"):
        return None
    cache_dir = (cache_dir or os.environ.get("UT_COMPILE_CACHE_DIR")
                 or default_cache_dir())
    if subdir:
        cache_dir = os.path.join(cache_dir, subdir)
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        return None  # older jax without the persistent cache: no-op
    return cache_dir
