"""Shared utilities: platform guards, logging, observability."""
