"""Small shared networking guards (no repo-internal imports, so both
the serve client and the telemetry shipper can use them without
coupling the planes)."""
from __future__ import annotations

import socket


def reject_self_connect(sock: socket.socket, label: str) -> None:
    """Close and refuse a TCP self-connection.

    Dialing a DOWN localhost port in the ephemeral range can land the
    client's own local port on the target and connect the socket to
    itself (the TCP simultaneous-open quirk): the "connection" answers
    nothing and, worse, HOLDS the port against the very server restart
    a resuming client is waiting for.  Callers invoke this right after
    ``create_connection``; it raises ``ConnectionRefusedError`` (an
    OSError, so every reconnect-with-backoff loop treats it like any
    refused dial)."""
    if sock.getsockname() == sock.getpeername():
        try:
            sock.close()
        except OSError:
            pass
        raise ConnectionRefusedError(
            f"self-connection to {label} (peer down)")
