"""Search-space layer: parameter specs + flat device encoding."""
from .params import (  # noqa: F401
    FLOAT, INT, LOG_FLOAT, LOG_INT, POW2, BOOL, SWITCH, ENUM,
    ParamSpec, FloatParam, IntParam, LogFloatParam, LogIntParam, Pow2Param,
    BoolParam, SwitchParam, EnumParam, PermParam, ScheduleParam,
    SelectorParam, ArrayParam, BoolArrayParam, IntArrayParam,
    FloatArrayParam, infer_param,
)
from .spec import CandBatch, Space, concat_cands, pad_cands  # noqa: F401
