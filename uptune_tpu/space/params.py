"""Host-side parameter specifications for uptune-tpu search spaces.

These are the declarative equivalents of the reference's parameter classes
(`/root/reference/python/uptune/opentuner/search/manipulator.py:275-1484`),
but they carry *no* mutation logic: all operators act on the flat device
encoding (see `uptune_tpu.space.spec.Space`), so a param spec only describes
the value domain and how a scalar dimension maps between the unit interval
[0, 1] and user-facing values.

Scalar-dimension kinds (the `kind` codes stored per dimension in a Space):

==========  ======================================================
FLOAT       continuous in [lo, hi]             (manipulator.py:703)
INT         integer in [lo, hi]                (manipulator.py:651)
LOG_FLOAT   float searched on log2 scale       (manipulator.py:800)
LOG_INT     integer searched on log2 scale     (manipulator.py:781)
POW2        power of two, searched by exponent (manipulator.py:813)
BOOL        True/False                         (manipulator.py:930)
SWITCH      unordered choice of range(n)       (manipulator.py:999)
ENUM        unordered choice from options list (manipulator.py:1024)
==========  ======================================================

BOOL / SWITCH / ENUM are "complex" (non-cartesian) in the reference: the
differential-evolution linear-combination op degenerates to
randomize-if-parents-differ for them (manipulator.py:866-917).  We keep a
unit-interval storage for them too (so every scalar dim is one f32 lane) but
operators consult the per-dim `complex` mask to reproduce that semantic.

Permutations (PermParam / ScheduleParam, manipulator.py:1048-1445) are stored
as separate fixed-width int32 blocks, not unit lanes.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Scalar kind codes (stored in Space.kind as int32).
FLOAT = 0
INT = 1
LOG_FLOAT = 2
LOG_INT = 3
POW2 = 4
BOOL = 5
SWITCH = 6
ENUM = 7

# kinds >= COMPLEX_KIND_START use complex-parameter (randomize-if-differ)
# semantics for linear-combination operators.
COMPLEX_KIND_START = BOOL

_KIND_NAMES = {
    FLOAT: "float", INT: "int", LOG_FLOAT: "log_float", LOG_INT: "log_int",
    POW2: "pow2", BOOL: "bool", SWITCH: "switch", ENUM: "enum",
}


class ParamSpec:
    """Base class for all parameter specs. Scalar specs contribute exactly one
    unit-interval lane; permutation specs contribute one int32 block."""

    name: str

    @property
    def is_permutation(self) -> bool:
        return False

    def search_space_size(self) -> float:
        raise NotImplementedError


@dataclass(frozen=True)
class _ScalarSpec(ParamSpec):
    name: str

    @property
    def kind(self) -> int:
        raise NotImplementedError

    # --- unit mapping -----------------------------------------------------
    # Every scalar spec defines the *search-scale* range (slo, shi) that the
    # unit interval maps onto, mirroring `legal_range` + the integer
    # +-0.4999 rounding widening of manipulator.py:473-503.
    def scaled_range(self) -> Tuple[float, float]:
        raise NotImplementedError


@dataclass(frozen=True)
class FloatParam(_ScalarSpec):
    lo: float = 0.0
    hi: float = 1.0

    def __post_init__(self):
        assert self.lo <= self.hi, (self.name, self.lo, self.hi)

    @property
    def kind(self) -> int:
        return FLOAT

    def scaled_range(self):
        return float(self.lo), float(self.hi)

    def search_space_size(self):
        return 2.0 ** 32


@dataclass(frozen=True)
class IntParam(_ScalarSpec):
    lo: int = 0
    hi: int = 1

    def __post_init__(self):
        assert self.lo <= self.hi, (self.name, self.lo, self.hi)
        # decoded integers are hashed as int32 (spec.canonical_lanes)
        assert -2**31 < self.lo and self.hi < 2**31, (self.name, "range must fit int32")

    @property
    def kind(self) -> int:
        return INT

    def scaled_range(self):
        # integer rounding widening, manipulator.py:477-480
        return self.lo - 0.4999, self.hi + 0.4999

    def search_space_size(self):
        return float(self.hi - self.lo + 1)


@dataclass(frozen=True)
class LogFloatParam(_ScalarSpec):
    lo: float = 0.0
    hi: float = 1.0

    def __post_init__(self):
        assert self.lo <= self.hi, (self.name, self.lo, self.hi)

    @property
    def kind(self) -> int:
        return LOG_FLOAT

    def scaled_range(self):
        # scale(v) = log2(v + 1 - lo), manipulator.py:800-810
        return 0.0, math.log2(self.hi + 1.0 - self.lo)

    def search_space_size(self):
        return 2.0 ** 32


@dataclass(frozen=True)
class LogIntParam(_ScalarSpec):
    lo: int = 0
    hi: int = 1

    def __post_init__(self):
        assert self.lo <= self.hi, (self.name, self.lo, self.hi)
        assert -2**31 < self.lo and self.hi < 2**31, (self.name, "range must fit int32")

    @property
    def kind(self) -> int:
        return LOG_INT

    def scaled_range(self):
        # widen by 0.4999 *before* scaling, manipulator.py:781-797
        return (math.log2(max(self.lo - 0.4999, -0.999) + 1.0 - self.lo),
                math.log2(self.hi + 0.4999 + 1.0 - self.lo))

    def search_space_size(self):
        return float(self.hi - self.lo + 1)


@dataclass(frozen=True)
class Pow2Param(_ScalarSpec):
    lo: int = 1
    hi: int = 1

    def __post_init__(self):
        assert self.lo >= 1 and self.hi >= self.lo
        assert math.log2(self.lo) % 1 == 0, self.lo
        assert math.log2(self.hi) % 1 == 0, self.hi
        # decoded powers of two are hashed as int32 (spec.canonical_lanes)
        assert self.hi < 2**31, (self.name, "max value must fit int32")

    @property
    def kind(self) -> int:
        return POW2

    @property
    def exp_lo(self) -> int:
        return int(math.log2(self.lo))

    @property
    def exp_hi(self) -> int:
        return int(math.log2(self.hi))

    def scaled_range(self):
        # searched by integer exponent, manipulator.py:813-836
        return self.exp_lo - 0.4999, self.exp_hi + 0.4999

    def search_space_size(self):
        return float(self.exp_hi - self.exp_lo + 1)


@dataclass(frozen=True)
class BoolParam(_ScalarSpec):
    @property
    def kind(self) -> int:
        return BOOL

    def scaled_range(self):
        return -0.4999, 1.4999

    def search_space_size(self):
        return 2.0


@dataclass(frozen=True)
class SwitchParam(_ScalarSpec):
    n: int = 2

    def __post_init__(self):
        assert self.n >= 1

    @property
    def kind(self) -> int:
        return SWITCH

    def scaled_range(self):
        return -0.4999, self.n - 1 + 0.4999

    def search_space_size(self):
        return float(max(1, self.n))


@dataclass(frozen=True)
class EnumParam(_ScalarSpec):
    options: Tuple[Any, ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "options", tuple(self.options))
        assert len(self.options) >= 1, self.name

    @property
    def kind(self) -> int:
        return ENUM

    def scaled_range(self):
        return -0.4999, len(self.options) - 1 + 0.4999

    def search_space_size(self):
        return float(max(1, len(self.options)))


@dataclass(frozen=True)
class PermParam(ParamSpec):
    """An ordering of `items` (manipulator.py:1048).  Encoded as an int32
    vector of item *indices*; decode maps back through `items`."""
    name: str
    items: Tuple[Any, ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "items", tuple(self.items))
        assert len(self.items) >= 1

    @property
    def is_permutation(self) -> bool:
        return True

    @property
    def size(self) -> int:
        return len(self.items)

    def search_space_size(self):
        return float(math.factorial(max(1, len(self.items))))


@dataclass(frozen=True)
class ScheduleParam(PermParam):
    """Dependency-respecting permutation (manipulator.py:1359-1445).

    `deps` maps item -> items that must come earlier.  Normalisation
    topologically sorts candidate orderings; the dependency closure is
    precomputed host-side into a boolean matrix used by the batched
    topo-normalise kernel (ops/perm.py).
    """
    deps: Tuple[Tuple[Any, Tuple[Any, ...]], ...] = ()

    def __post_init__(self):
        super().__post_init__()
        # normalize deps into a hashable tuple-of-tuples and expand the
        # transitive closure exactly as manipulator.py:1367-1390.
        dep_map: Dict[Any, set] = {k: set(v) for k, v in dict(self.deps).items()}
        changed = True
        while changed:
            changed = False
            for k in list(dep_map):
                before = len(dep_map[k])
                for d in list(dep_map[k]):
                    if d in dep_map:
                        dep_map[k] |= dep_map[d]
                if len(dep_map[k]) != before:
                    changed = True
        items = set(self.items)
        for k, v in dep_map.items():
            if k in v:
                raise ValueError(
                    f"ScheduleParam({self.name!r}) cycle: {k!r} depends on itself")
            if v - items:
                raise ValueError(
                    f"ScheduleParam({self.name!r}): unknown deps {v - items!r}")
        if set(dep_map) - items:
            raise ValueError(
                f"ScheduleParam({self.name!r}): unknown items {set(dep_map) - items!r}")
        object.__setattr__(
            self, "deps",
            tuple(sorted(((k, tuple(sorted(v, key=self.items.index)))
                          for k, v in dep_map.items() if v),
                         key=lambda kv: self.items.index(kv[0]))))

    def dep_matrix(self) -> List[List[bool]]:
        """dep_matrix[i][j] is True iff items[i] requires items[j] earlier."""
        idx = {it: i for i, it in enumerate(self.items)}
        n = len(self.items)
        mat = [[False] * n for _ in range(n)]
        for k, vs in self.deps:
            for v in vs:
                mat[idx[k]][idx[v]] = True
        return mat


@dataclass(frozen=True)
class SelectorParam(_ScalarSpec):
    """Ordered choice: an integer position in [0, max_cutoff) mapped onto
    `choices` by equal intervals.  The reference's SelectorParameter
    (manipulator.py:1448-1484) searches over explicit cutoff lists; the
    TPU-first simplification keeps its essential property — ADJACENT
    positions map to the same or neighboring choice, so ordinary numeric
    mutation moves between related choices — in one INT lane with fixed
    interval boundaries."""
    choices: Tuple[Any, ...] = ()
    max_cutoff: int = 0

    def __post_init__(self):
        object.__setattr__(self, "choices", tuple(self.choices))
        assert len(self.choices) >= 1, self.name
        mc = self.max_cutoff or len(self.choices)
        object.__setattr__(self, "max_cutoff", int(mc))
        assert self.max_cutoff >= len(self.choices), self.name

    @property
    def kind(self) -> int:
        return INT   # ordered lane, NOT complex: locality is the point

    def scaled_range(self):
        return -0.4999, self.max_cutoff - 1 + 0.4999

    def choice_of(self, pos: int) -> Any:
        i = int(pos) * len(self.choices) // self.max_cutoff
        return self.choices[min(max(i, 0), len(self.choices) - 1)]

    def pos_of(self, choice: Any) -> int:
        i = self.choices.index(choice)
        # center of the choice's interval
        return min((2 * i + 1) * self.max_cutoff // (2 * len(self.choices)),
                   self.max_cutoff - 1)

    def search_space_size(self):
        return float(self.max_cutoff)


class ArrayParam(ParamSpec):
    """Base for fixed-length array parameters (manipulator.py:1484-1732
    ParameterArray / BooleanArray / FloatArray / Array): expands into n
    scalar lanes named ``name[i]`` at Space build time; the config value
    is one Python list."""

    name: str
    n: int

    def expand(self) -> List[_ScalarSpec]:
        raise NotImplementedError

    def search_space_size(self) -> float:
        out = 1.0
        for s in self.expand():
            out *= s.search_space_size()
        return out


@dataclass(frozen=True)
class BoolArrayParam(ArrayParam):
    name: str = ""
    n: int = 1

    def __post_init__(self):
        assert self.n >= 1, self.name

    def expand(self):
        return [BoolParam(f"{self.name}[{i}]") for i in range(self.n)]


@dataclass(frozen=True)
class IntArrayParam(ArrayParam):
    name: str = ""
    n: int = 1
    lo: int = 0
    hi: int = 1

    def __post_init__(self):
        assert self.n >= 1, self.name

    def expand(self):
        return [IntParam(f"{self.name}[{i}]", lo=self.lo, hi=self.hi)
                for i in range(self.n)]


@dataclass(frozen=True)
class FloatArrayParam(ArrayParam):
    name: str = ""
    n: int = 1
    lo: float = 0.0
    hi: float = 1.0

    def __post_init__(self):
        assert self.n >= 1, self.name

    def expand(self):
        return [FloatParam(f"{self.name}[{i}]", lo=self.lo, hi=self.hi)
                for i in range(self.n)]


def infer_param(name: str, default: Any, space: Any) -> ParamSpec:
    """Infer a ParamSpec from a `ut.tune(default, space)` call, mirroring the
    type-dispatch of the reference's tune API
    (`/root/reference/python/uptune/template/tuneapi.py:35-93`)."""
    if isinstance(space, (list,)) and not isinstance(default, (list,)):
        return EnumParam(name, options=tuple(space))
    if isinstance(space, tuple) and len(space) == 2:
        lo, hi = space
        if isinstance(default, bool):
            return BoolParam(name)
        if isinstance(default, int) and isinstance(lo, int) and isinstance(hi, int):
            return IntParam(name, lo=lo, hi=hi)
        return FloatParam(name, lo=float(lo), hi=float(hi))
    if isinstance(default, bool):
        return BoolParam(name)
    if isinstance(default, list) and isinstance(space, list):
        # permutation: space is the item set, default the initial ordering
        return PermParam(name, items=tuple(space))
    raise TypeError(
        f"cannot infer parameter type for {name!r}: default={default!r} space={space!r}")
