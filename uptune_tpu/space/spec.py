"""The flat, device-resident search-space encoding.

This replaces the reference's `ConfigurationManipulator` + dict-of-values
configurations (`/root/reference/python/uptune/opentuner/search/
manipulator.py:129-272`) with a fixed-shape array encoding so that whole
*batches* of candidate configurations live on TPU:

* every scalar parameter is one float32 lane holding a **unit value** in
  [0, 1] — exactly the scale the reference searches primitives on
  (`get_unit_value`/`set_unit_value`, manipulator.py:473-503);
* every permutation parameter is one int32 block of item indices.

A batch of B candidates over a space with D scalar lanes and perm blocks of
sizes (s0, s1, ...) is a `CandBatch(u=[B, D] f32, perms=([B, s0] i32, ...))`
pytree.  All mutation / crossover operators (uptune_tpu.ops) and all search
techniques act on this representation; decode back to user values happens
only at the evaluation boundary.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import params as P


class CandBatch(NamedTuple):
    """A batch of candidate configurations in flat device encoding."""
    u: jax.Array                    # [B, D] float32 unit values
    perms: Tuple[jax.Array, ...]    # each [B, size_k] int32 item indices

    @property
    def batch(self) -> int:
        return self.u.shape[0]

    def __getitem__(self, idx) -> "CandBatch":
        # NamedTuple would otherwise give positional indexing; we want
        # batch-axis selection so `cands[mask]` / `cands[topk]` just work.
        if isinstance(idx, int) and not isinstance(idx, bool):
            raise TypeError("use slices/arrays; scalar indexing drops the batch dim")
        return CandBatch(self.u[idx], tuple(p[idx] for p in self.perms))

    def concat(self, other: "CandBatch") -> "CandBatch":
        return CandBatch(
            jnp.concatenate([self.u, other.u], axis=0),
            tuple(jnp.concatenate([a, b], axis=0)
                  for a, b in zip(self.perms, other.perms)))


def concat_cands(cands: Sequence[CandBatch]) -> CandBatch:
    return CandBatch(
        jnp.concatenate([c.u for c in cands], axis=0),
        tuple(jnp.concatenate(ps, axis=0)
              for ps in zip(*[c.perms for c in cands])))


def pad_cands(cands: CandBatch, n: int) -> CandBatch:
    """Pad the batch axis to `n` rows by repeating row 0 (jittable,
    static shapes).  The driver pads every arm's proposal to one common
    bucket size so its dedup/commit programs see ONE input aval and
    trace once instead of once per arm batch shape; a padding row is an
    exact in-batch duplicate of row 0, so `dup_source`/`unique_mask`
    classify it as non-novel and it can never become a trial or enter
    the history."""
    b = cands.batch
    if b >= n:
        return cands
    pad = n - b
    return CandBatch(
        jnp.concatenate(
            [cands.u, jnp.broadcast_to(cands.u[:1], (pad,) +
                                       cands.u.shape[1:])], axis=0),
        tuple(jnp.concatenate(
            [p, jnp.broadcast_to(p[:1], (pad,) + p.shape[1:])], axis=0)
            for p in cands.perms))


class Space:
    """Static (host-side, hashable-by-id) description of a search space plus
    the numpy/JAX constant tables used by the device codecs.

    The table layout mirrors what the reference spreads across parameter
    objects: per-lane kind, search-scale bounds (slo/shi), decoded-value
    bounds (vlo/vhi), and the complex-parameter mask that switches
    linear-combination operators to randomize-if-differ semantics
    (manipulator.py:866-917).
    """

    def __init__(self, specs: Sequence[P.ParamSpec]):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate parameter names: {names}")
        self.specs: Tuple[P.ParamSpec, ...] = tuple(specs)
        # ArrayParams expand into scalar lanes named "name[i]"; the
        # grouping map reassembles them into one list config value
        expanded: List[P.ParamSpec] = []
        self.array_groups: Dict[str, List[str]] = {}
        for s in specs:
            if isinstance(s, P.ArrayParam):
                children = s.expand()
                self.array_groups[s.name] = [c.name for c in children]
                expanded.extend(children)
            else:
                expanded.append(s)
        exp_names = [s.name for s in expanded]
        if len(set(exp_names)) != len(exp_names):
            dups = sorted({n for n in exp_names if exp_names.count(n) > 1})
            raise ValueError(
                f"parameter names collide after array expansion: {dups}")
        self.scalars: Tuple[P._ScalarSpec, ...] = tuple(
            s for s in expanded if not s.is_permutation)
        self.perm_specs: Tuple[P.PermParam, ...] = tuple(
            s for s in expanded if s.is_permutation)
        self.name_to_spec = {s.name: s for s in specs}

        D = len(self.scalars)
        kind = np.zeros(D, np.int32)
        slo = np.zeros(D, np.float32)
        shi = np.zeros(D, np.float32)
        vlo = np.zeros(D, np.float32)
        vhi = np.zeros(D, np.float32)
        for i, s in enumerate(self.scalars):
            kind[i] = s.kind
            a, b = s.scaled_range()
            slo[i], shi[i] = a, b
            if isinstance(s, P.SelectorParam):
                vlo[i], vhi[i] = 0, s.max_cutoff - 1
            elif isinstance(s, (P.FloatParam, P.IntParam, P.LogFloatParam,
                                P.LogIntParam)):
                vlo[i], vhi[i] = float(s.lo), float(s.hi)
            elif isinstance(s, P.Pow2Param):
                vlo[i], vhi[i] = s.exp_lo, s.exp_hi  # exponent bounds
            elif isinstance(s, P.BoolParam):
                vlo[i], vhi[i] = 0, 1
            elif isinstance(s, P.SwitchParam):
                vlo[i], vhi[i] = 0, s.n - 1
            elif isinstance(s, P.EnumParam):
                vlo[i], vhi[i] = 0, len(s.options) - 1
            else:  # pragma: no cover
                raise TypeError(s)
        self.kind = jnp.asarray(kind)
        self.slo = jnp.asarray(slo)
        self.shi = jnp.asarray(shi)
        self.vlo = jnp.asarray(vlo)
        self.vhi = jnp.asarray(vhi)
        # lanes with integer-valued decodes (hash on the integer)
        self._int_mask_np = np.isin(
            kind, [P.INT, P.LOG_INT, P.POW2, P.BOOL, P.SWITCH, P.ENUM])
        self.int_mask = jnp.asarray(self._int_mask_np)
        # lanes using complex-parameter (randomize-if-differ) semantics
        self.complex_mask = jnp.asarray(kind >= P.COMPLEX_KIND_START)
        # truly CATEGORICAL lanes (unordered codes — BOOL/SWITCH/ENUM):
        # surrogate features one-hot these so the GP's Hamming kernel and
        # the pool's code-flip moves treat "default"/"on"/"off" as
        # equidistant instead of imposing the unit-lane ordering
        # (SelectorParam is ordered by design, so it stays numeric)
        self._cat_mask_np = np.isin(kind, [P.BOOL, P.SWITCH, P.ENUM])
        self.cat_lane_idx = np.nonzero(self._cat_mask_np)[0]
        self.num_lane_idx = np.nonzero(~self._cat_mask_np)[0]
        self.n_cat = int(self._cat_mask_np.sum())
        # codes per categorical lane (vlo is 0 for these kinds)
        self.cat_code_counts = (vhi[self._cat_mask_np] + 1).astype(np.int32)
        self.cat_max_codes = (int(self.cat_code_counts.max())
                              if self.n_cat else 0)
        self.n_scalar = D
        self.perm_sizes: Tuple[int, ...] = tuple(p.size for p in self.perm_specs)
        # dependency matrices for ScheduleParams ([] entry = no constraint)
        self.perm_dep_mats: Tuple[Any, ...] = tuple(
            jnp.asarray(np.array(p.dep_matrix(), dtype=bool))
            if isinstance(p, P.ScheduleParam) else None
            for p in self.perm_specs)
        # universal-hash multipliers (fixed seed => stable across runs/resume)
        rng = np.random.RandomState(0x5EED)
        n_lanes = D + sum(self.perm_sizes)
        self._hash_mults = jnp.asarray(
            (rng.randint(0, 2**31, size=(2, max(1, n_lanes)), dtype=np.int64)
             * 2 + 1).astype(np.uint32))

    # -- python niceties ---------------------------------------------------
    def __len__(self) -> int:
        return len(self.specs)

    def __repr__(self) -> str:
        return (f"Space(D={self.n_scalar} scalar lanes, "
                f"perms={list(self.perm_sizes)}, params={len(self.specs)})")

    def signature(self) -> List[str]:
        """Ordered structural signature: spec dataclass reprs carry
        name, kind, bounds, options/items.  Shared identity across the
        planes that must agree on "the same space": the driver's
        archive header (position-indexed unit-vector replay), the
        results store's scope key, and the session server's tenant
        grouping (equal signatures => one BatchedEngine instance
        axis)."""
        return [repr(s) for s in self.specs]

    def search_space_size(self) -> float:
        """Product of per-parameter sizes (manipulator.py:245-247)."""
        out = 1.0
        for s in self.specs:
            out *= s.search_space_size()
        return out

    # -- device codecs -----------------------------------------------------
    def decode_scalars(self, u: jax.Array) -> jax.Array:
        """Unit lanes [..., D] -> decoded values [..., D] float32.

        Reproduces `set_unit_value` semantics per kind (manipulator.py:
        489-503): scale into [slo, shi], round for integer types, clamp to
        the legal range.  BOOL/SWITCH/ENUM decode to their integer code;
        ENUM option objects are applied host-side in `to_configs`.
        POW2 decodes to the power-of-two *value*.
        """
        s = u * (self.shi - self.slo) + self.slo
        kind = self.kind
        val = s  # FLOAT
        # INT: round+clamp in value space
        val = jnp.where(kind == P.INT,
                        jnp.clip(jnp.round(s), self.vlo, self.vhi), val)
        # LOG_FLOAT: 2**s - 1 + lo   (vlo == lo), computed as
        # expm1(s*ln2) + lo to avoid the catastrophic cancellation of
        # exp2(s) - 1 near s == 0 in f32
        ln2 = 0.6931471805599453
        log_val = jnp.expm1(s * ln2) + self.vlo
        val = jnp.where(kind == P.LOG_FLOAT, log_val, val)
        # LOG_INT: round(2**s - 1 + lo) clamped
        val = jnp.where(kind == P.LOG_INT,
                        jnp.clip(jnp.round(log_val), self.vlo, self.vhi), val)
        # POW2: 2**round(exponent)
        val = jnp.where(kind == P.POW2,
                        jnp.exp2(jnp.clip(jnp.round(s), self.vlo, self.vhi)),
                        val)
        # BOOL / SWITCH / ENUM: integer code
        code = jnp.clip(jnp.round(s), self.vlo, self.vhi)
        val = jnp.where(kind >= P.BOOL, code, val)
        return val.astype(jnp.float32)

    def encode_scalars(self, vals: jax.Array) -> jax.Array:
        """Decoded values [..., D] -> unit lanes, inverse of decode_scalars
        (mirrors `get_unit_value`, manipulator.py:473-488)."""
        kind = self.kind
        s = vals  # FLOAT / INT-style value space
        # log kinds: s = log2(v + 1 - lo) = log1p(v - lo) / ln2, the
        # well-conditioned companion of the expm1 decode above
        inv_ln2 = 1.4426950408889634
        s = jnp.where((kind == P.LOG_FLOAT) | (kind == P.LOG_INT),
                      jnp.log1p(jnp.maximum(vals - self.vlo, -0.999)) * inv_ln2,
                      s)
        s = jnp.where(kind == P.POW2,
                      jnp.log2(jnp.maximum(vals, 1.0)), s)
        rng = jnp.maximum(self.shi - self.slo, 1e-30)
        return jnp.clip((s - self.slo) / rng, 0.0, 1.0).astype(jnp.float32)

    def random(self, key: jax.Array, n: int) -> CandBatch:
        """Uniform random batch (the batched `manipulator.random()`)."""
        ku, *kp = jax.random.split(key, 1 + max(1, len(self.perm_sizes)))
        u = jax.random.uniform(ku, (n, self.n_scalar), dtype=jnp.float32)
        perms = []
        for size, k, dep in zip(self.perm_sizes, kp, self.perm_dep_mats):
            pm = jax.vmap(lambda kk: jax.random.permutation(kk, size))(
                jax.random.split(k, n)).astype(jnp.int32)
            perms.append(pm)
        cands = CandBatch(u, tuple(perms))
        return self.normalize(cands)

    def seed_default(self, n: int) -> CandBatch:
        """Batch of n copies of the seed (default) configuration: scalar
        seed = lo (NumericParameter.seed_value, manipulator.py:581-583),
        perm seed = identity ordering (manipulator.py:1084-1085)."""
        u0 = self.encode_scalars(
            jnp.where(self.kind == P.POW2, jnp.exp2(self.vlo), self.vlo))
        u = jnp.tile(u0[None, :], (n, 1))
        perms = tuple(
            jnp.tile(jnp.arange(size, dtype=jnp.int32)[None, :], (n, 1))
            for size in self.perm_sizes)
        return self.normalize(CandBatch(u, perms))

    def normalize(self, cands: CandBatch) -> CandBatch:
        """Topologically normalise ScheduleParam blocks (manipulator.py:
        1425-1445); other blocks pass through."""
        from ..ops import perm as perm_ops  # local import to avoid cycle
        perms = tuple(
            perm_ops.toposort_batch(pm, dep) if dep is not None else pm
            for pm, dep in zip(cands.perms, self.perm_dep_mats))
        return CandBatch(cands.u, perms)

    def canonical_lanes(self, cands: CandBatch) -> jax.Array:
        """[B, n_lanes] int32 canonical representation used for hashing:
        integer lanes use their decoded integer, float lanes bitcast the
        decoded f32, perm blocks append their indices.  Equal configs map to
        equal lanes (the analogue of `hash_config`, manipulator.py:233-243)."""
        vals = self.decode_scalars(cands.u)
        as_int = jnp.round(vals).astype(jnp.int32)
        # Float lanes hash on a 2^16 unit-space grid rather than the decoded
        # value: decode of log-scaled params (2^s - 1 + lo) cancels
        # catastrophically in f32 near the low end, so value-space hashing
        # is not stable under an encode/decode round-trip (archive replay
        # via from_configs).  The unit transform is well-conditioned in both
        # directions, so quantizing u is round-trip robust; it also defines
        # dedup granularity: float configs closer than 2^-16 of the search
        # range count as the same configuration.
        as_grid = jnp.round(cands.u * 65536.0).astype(jnp.int32)
        lanes = jnp.where(self.int_mask, as_int, as_grid)
        parts = [lanes] + [p.astype(jnp.int32) for p in cands.perms]
        return jnp.concatenate(parts, axis=-1) if len(parts) > 1 else lanes

    @property
    def n_features(self) -> int:
        return self.n_scalar + sum(self.perm_sizes)

    def features(self, cands: CandBatch) -> jax.Array:
        """[B, n_features] f32 surrogate-model features: scalar unit lanes
        as-is; each permutation block contributes the normalized POSITION
        of every item in the ordering (a fixed-width, smooth-ish embedding
        of the permutation — the analogue of the reference's flat feature
        vectors fed to XGBoost, plugins/xgbregressor.py:55,67)."""
        parts = [cands.u]
        for pm, size in zip(cands.perms, self.perm_sizes):
            # position of item i in the ordering == inverse permutation
            pos = jnp.argsort(pm, axis=-1).astype(jnp.float32) / max(
                1, size - 1)
            parts.append(pos)
        return jnp.concatenate(parts, axis=-1) if len(parts) > 1 else parts[0]

    @property
    def n_cont_features(self) -> int:
        """Leading continuous-block width of surrogate_transform output."""
        return (self.n_scalar - self.n_cat) + sum(self.perm_sizes)

    @property
    def n_surrogate_features(self) -> int:
        # one-hot blocks are padded to cat_max_codes per lane (vectorized
        # encode); padding columns are identically 0 on both sides of any
        # distance, so they are inert
        return self.n_cont_features + self.n_cat * self.cat_max_codes

    def surrogate_transform(self, feats: jax.Array) -> jax.Array:
        """features() output [B, n_features] -> surrogate representation
        [B, n_cont_features + sum(cat codes)]:

        * numeric lanes snapped to their decoded grid (an encode∘decode
          round-trip), so two configs that decode to the same integer are
          IDENTICAL in feature space — the raw unit lane carries
          sub-rounding noise the model would otherwise have to explain;
        * permutation position lanes passed through;
        * categorical lanes (BOOL/SWITCH/ENUM) one-hot encoded, scaled by
          1/sqrt(2) so the squared euclidean distance over the block
          EQUALS the Hamming distance (# differing lanes) — the GP's
          categorical kernel reads it straight off one MXU matmul, and
          unordered codes become equidistant (the reference's XGBoost
          trees got this for free by splitting; a distance-based model
          must be told, VERDICT r3 next-step #2).

        Operates on features (not CandBatch) so the driver's existing
        observe(space.features(...)) plumbing needs no change.
        """
        D = self.n_scalar
        u = feats[..., :D]
        rest = feats[..., D:]   # perm position lanes
        u_snap = self.encode_scalars(self.decode_scalars(u))
        parts = [u_snap[..., self.num_lane_idx], rest]
        if self.n_cat:
            codes = self.decode_scalars(u)[..., self.cat_lane_idx]
            oh = (codes[..., None]
                  == jnp.arange(self.cat_max_codes, dtype=jnp.float32))
            oh = oh.reshape(*codes.shape[:-1],
                            self.n_cat * self.cat_max_codes)
            parts.append(oh.astype(jnp.float32) * float(1.0 / np.sqrt(2)))
        return jnp.concatenate(parts, axis=-1)

    def hash_batch(self, cands: CandBatch) -> jax.Array:
        """[B] uint64-equivalent hash as a [B, 2] uint32 pair (multiply-shift
        universal hashing; device-side replacement for the reference's
        sha256-of-repr config hashing, manipulator.py:233-243)."""
        lanes = self.canonical_lanes(cands).astype(jnp.uint32)
        h = (lanes[..., None, :] * self._hash_mults).sum(axis=-1)
        return h.astype(jnp.uint32)  # [B, 2]

    # -- host codecs (evaluation boundary) ---------------------------------
    # These run in float64 numpy: XLA's f32 transcendentals are only ~3e-5
    # accurate, so a device-side decode->encode round-trip of log-scaled
    # params would drift across hash-grid boundaries.  Host decode and host
    # encode are exact inverses to f64 precision, which makes archive
    # replay (from_configs of to_configs output) hash-stable; the device
    # decode (decode_scalars) agrees with the host decode to f32
    # transcendental accuracy, which only matters for surrogate features.
    def decode_scalars_np(self, u: np.ndarray) -> np.ndarray:
        kind = np.asarray(self.kind)
        slo = np.asarray(self.slo, np.float64)
        shi = np.asarray(self.shi, np.float64)
        vlo = np.asarray(self.vlo, np.float64)
        vhi = np.asarray(self.vhi, np.float64)
        s = np.asarray(u, np.float64) * (shi - slo) + slo
        val = s.copy()
        m = kind == P.INT
        val[..., m] = np.clip(np.round(s[..., m]), vlo[m], vhi[m])
        m = kind == P.LOG_FLOAT
        val[..., m] = np.expm1(s[..., m] * np.log(2.0)) + vlo[m]
        m = kind == P.LOG_INT
        val[..., m] = np.clip(np.round(np.expm1(s[..., m] * np.log(2.0)) + vlo[m]),
                              vlo[m], vhi[m])
        m = kind == P.POW2
        val[..., m] = np.exp2(np.clip(np.round(s[..., m]), vlo[m], vhi[m]))
        m = kind >= P.BOOL
        val[..., m] = np.clip(np.round(s[..., m]), vlo[m], vhi[m])
        return val

    def encode_scalars_np(self, vals: np.ndarray) -> np.ndarray:
        kind = np.asarray(self.kind)
        slo = np.asarray(self.slo, np.float64)
        shi = np.asarray(self.shi, np.float64)
        vlo = np.asarray(self.vlo, np.float64)
        s = np.asarray(vals, np.float64).copy()
        m = (kind == P.LOG_FLOAT) | (kind == P.LOG_INT)
        s[..., m] = np.log1p(np.maximum(s[..., m] - vlo[m], -0.999)) / np.log(2.0)
        m = kind == P.POW2
        s[..., m] = np.log2(np.maximum(s[..., m], 1.0))
        rng = np.maximum(shi - slo, 1e-30)
        return np.clip((s - slo) / rng, 0.0, 1.0).astype(np.float32)

    def to_configs(self, cands: CandBatch) -> List[Dict[str, Any]]:
        """Decode a device batch into user-facing config dicts."""
        vals = self.decode_scalars_np(np.asarray(cands.u))
        perms = [np.asarray(p) for p in cands.perms]
        out: List[Dict[str, Any]] = []
        for b in range(vals.shape[0]):
            cfg: Dict[str, Any] = {}
            for i, s in enumerate(self.scalars):
                v = vals[b, i]
                if isinstance(s, P.SelectorParam):
                    cfg[s.name] = s.choice_of(int(round(float(v))))
                elif isinstance(s, P.FloatParam) or isinstance(s, P.LogFloatParam):
                    cfg[s.name] = float(v)
                elif isinstance(s, P.EnumParam):
                    cfg[s.name] = s.options[int(round(float(v)))]
                elif isinstance(s, P.BoolParam):
                    cfg[s.name] = bool(round(float(v)))
                else:  # INT / LOG_INT / POW2 / SWITCH
                    cfg[s.name] = int(round(float(v)))
            for k, s in enumerate(self.perm_specs):
                cfg[s.name] = [s.items[int(i)] for i in perms[k][b]]
            for parent, children in self.array_groups.items():
                cfg[parent] = [cfg.pop(c) for c in children]
            out.append(cfg)
        return out

    def from_configs(self, cfgs: Sequence[Dict[str, Any]]) -> CandBatch:
        """Encode user config dicts into a device batch (seed configs).

        Hash-stability contract: from_configs(to_configs(x)) hashes equal to
        x on every lane except LOG_INT lanes with ranges wider than ~2^15,
        where XLA's ~3e-5-relative f32 transcendentals can shift the device
        decode by an integer (observed ~5% of rows at a 2^20 range).  Exact
        resume therefore replays raw unit vectors from the archive (see
        driver.history), not configs; this path is for user-provided seeds
        where an occasional duplicate evaluation is harmless.
        """
        B = len(cfgs)
        if self.array_groups:
            flat = []
            for cfg in cfgs:
                cfg = dict(cfg)
                for parent, children in self.array_groups.items():
                    seq = cfg.pop(parent)
                    if len(seq) != len(children):
                        raise ValueError(
                            f"array {parent!r} needs {len(children)} "
                            f"elements, got {len(seq)}")
                    cfg.update(zip(children, seq))
                flat.append(cfg)
            cfgs = flat
        vals = np.zeros((B, self.n_scalar), np.float64)
        for b, cfg in enumerate(cfgs):
            for i, s in enumerate(self.scalars):
                v = cfg[s.name]
                if isinstance(s, P.SelectorParam):
                    vals[b, i] = s.pos_of(v)
                elif isinstance(s, P.EnumParam):
                    vals[b, i] = s.options.index(v)
                elif isinstance(s, P.BoolParam):
                    vals[b, i] = float(bool(v))
                else:
                    vals[b, i] = float(v)
            # POW2 lanes hold the value; encode maps to exponent
        u = jnp.asarray(self.encode_scalars_np(vals))
        perms = []
        for k, s in enumerate(self.perm_specs):
            block = np.zeros((B, s.size), np.int32)
            for b, cfg in enumerate(cfgs):
                order = cfg[s.name]
                block[b] = [s.items.index(it) for it in order]
            perms.append(jnp.asarray(block))
        return self.normalize(CandBatch(u, tuple(perms)))
