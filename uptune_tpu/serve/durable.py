"""Durable session checkpoints: the serving plane's write-ahead log
(ISSUE 15, ROADMAP item 1a).

The store memoizes *builds*, but a session's committed state — its
published versions, incumbent, counters — lived only in RAM: one
``kill -9`` of the serving process vaporized every tenant.  This
module journals each session's **committed state transitions** as one
append-only, torn-tail-tolerant JSONL segment per session id:

``sess-<id>.jsonl`` under the checkpoint dir (by default
``<store-dir>/checkpoints`` — the store's directory-scan ignores
subdirectories, so the two planes share one tree)::

    {"ev": "open",   ... space records, seed, program, sense, ...}
    {"ev": "commit", "v": 1, "raw": [...], "best_cfg": ..., ...}
    {"ev": "commit", "v": 2, ...}
    {"ev": "close"}

Why this is *small*: sessions are already versioned snapshots
(serve/session.py), so a checkpoint is just the v -> v+1 delta on the
commit path — the measured raw batch (``None`` encodes NaN: JSON has
no NaN and a failure row must replay as one) plus the host-side
accounting (incumbent, counters, ticket cursor, quality state) that
replay cannot cheaply reconstruct in tell order.  Device state is
never serialized at all: ``propose`` is pure in the state, so
recovery replays the commit stream through the SAME compiled
``jit_propose_all``/``jit_commit_slot`` programs and lands on a state
**bitwise identical** to one that never died.

Write discipline is the store's segment rule: one complete JSON line
per record via a single ``O_APPEND`` write (readers can only ever see
an incomplete *tail* line, which `load` leaves unconsumed), with an
optional fsync knob for power-loss durability — plain ``os.write``
already survives process SIGKILL via the page cache, which is the
failure mode ``bench.py --failover`` prices.

Ordering contract (the zero-committed-tell-loss bound): the serving
op that *publishes* a version appends its commit record **before its
reply is written** (Session._drain_ckpt), so any ``committed: true``
a client ever observed is durable.  A crash between the in-RAM
commit and the append loses only an ack the client never received —
the client retries, recovery restores v, and the store memo (whose
``record`` also precedes the reply) re-fills the replayed epoch with
identical values.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .. import obs
from ..obs import faults

__all__ = ["CheckpointLog", "default_checkpoint_dir", "decode_raw",
           "encode_raw", "CKPT_PREFIX", "CKPT_SUFFIX"]

CKPT_PREFIX = "sess-"
CKPT_SUFFIX = ".jsonl"


def default_checkpoint_dir(store_dir: Optional[str],
                           work_dir: str) -> str:
    """``--durable`` without a path: checkpoints live under the store
    directory (the content-addressed tree is already the serving
    plane's durable home); with the store off, under the work dir's
    ut.serve tree."""
    if store_dir:
        return os.path.join(store_dir, "checkpoints")
    return os.path.join(work_dir, "ut.serve", "checkpoints")


def encode_raw(raw) -> List[Optional[float]]:
    """A measured epoch batch as JSON: None encodes NaN (failure
    rows) — allow_nan JSON is not JSON, and a replayed failure must
    stay a failure."""
    out: List[Optional[float]] = []
    for v in raw:
        f = float(v)
        out.append(f if f == f and abs(f) != float("inf") else None)
    return out


def decode_raw(enc: List[Optional[float]]) -> List[float]:
    return [float("nan") if v is None else float(v) for v in enc]


class CheckpointLog:
    """One serving process's checkpoint plane: per-session append-only
    segments under one directory.  Appends open/write/close the file
    per record — commit records are per *epoch* (a whole batch of
    tells), so the syscall cost is amortized far off the ask/tell hot
    path, and no fd table grows with the session count."""

    def __init__(self, root: str, *, fsync: bool = False):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.fsync = bool(fsync)
        # serializes same-session appends from concurrent handler
        # threads (two clients may drive one session); cross-session
        # appends never share a file, so one lock is contention-free
        # at the per-epoch append rate
        self._lock = threading.Lock()
        self.appends = 0
        self.errors = 0
        self.reaped = 0

    def path_for(self, sid: str) -> str:
        return os.path.join(self.root,
                            f"{CKPT_PREFIX}{sid}{CKPT_SUFFIX}")

    # -- writes --------------------------------------------------------
    def append(self, sid: str, record: Dict[str, Any]) -> bool:
        """Append one record as one complete line via a single
        O_APPEND write.  Returns False on OSError (counted, never
        raised: the tell is already applied in RAM — failing the
        reply for a disk hiccup would report ok=False for an epoch
        that really committed, the store-append rule)."""
        faults.fire("ckpt.append")
        data = (json.dumps(record, separators=(",", ":"),
                           allow_nan=False) + "\n").encode()
        fd = -1
        try:
            # open+write under the lock (same-session append order);
            # fsync OUTSIDE it — fsync flushes the whole inode, so by
            # the time THIS append's fsync returns, this record and
            # every earlier one are durable, and the caller's reply
            # still strictly follows its own record's durability.
            # Holding a lock across fsync serializes every concurrent
            # session behind one disk flush (R102).
            with self._lock:
                fd = os.open(self.path_for(sid),
                             os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                             0o644)
                os.write(fd, data)       # one write = one atomic line
            if self.fsync:
                os.fsync(fd)
        except OSError:
            self.errors += 1
            obs.count("serve.ckpt_errors")
            return False
        finally:
            if fd >= 0:
                os.close(fd)
        self.appends += 1
        obs.count("serve.ckpt_appends")
        return True

    def reap(self, sid: str) -> None:
        """Drop a closed session's segment (recovery also reaps any
        segment whose record stream ends in a close)."""
        try:
            os.unlink(self.path_for(sid))
            self.reaped += 1
        except OSError:
            pass

    # -- reads (recovery) ----------------------------------------------
    def load(self, sid: str) -> List[Dict[str, Any]]:
        """One session's surviving records, torn-tail tolerant: an
        incomplete or unparseable final line (the crash tail) is
        dropped; a bad line mid-file ends the usable prefix — records
        after it cannot be trusted to be contiguous."""
        out: List[Dict[str, Any]] = []
        try:
            with open(self.path_for(sid), "rb") as f:
                buf = f.read()
        except OSError:
            return out
        for line in buf.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                break
            if isinstance(rec, dict):
                out.append(rec)
        return out

    def session_ids(self) -> List[str]:
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        return [n[len(CKPT_PREFIX):-len(CKPT_SUFFIX)] for n in names
                if n.startswith(CKPT_PREFIX)
                and n.endswith(CKPT_SUFFIX)]

    def scan(self) -> Iterator[Tuple[str, Dict[str, Any]]]:
        """Yield ``(sid, bundle)`` per surviving segment, where bundle
        is ``{"open": rec | None, "commits": [recs sorted by v],
        "closed": bool}``.  Commit records are sorted and deduped by
        version (same-session drains from two handler threads may
        append out of order; versions are authoritative) and truncated
        at the first gap — replay must be contiguous from v=1."""
        for sid in self.session_ids():
            recs = self.load(sid)
            open_rec: Optional[Dict[str, Any]] = None
            closed = False
            by_v: Dict[int, Dict[str, Any]] = {}
            for r in recs:
                ev = r.get("ev")
                if ev == "open" and open_rec is None:
                    open_rec = r
                elif ev == "commit":
                    try:
                        v = int(r["v"])
                    except (KeyError, TypeError, ValueError):
                        continue
                    by_v.setdefault(v, r)
                elif ev == "close":
                    closed = True
            commits: List[Dict[str, Any]] = []
            for v in range(1, len(by_v) + len(recs) + 1):
                r = by_v.get(v)
                if r is None:
                    break
                commits.append(r)
            yield sid, {"open": open_rec, "commits": commits,
                        "closed": closed}

    def stats(self) -> Dict[str, Any]:
        return {"dir": self.root, "fsync": self.fsync,
                "appends": self.appends, "errors": self.errors,
                "reaped": self.reaped}
