"""One tenant's session: tickets over versioned snapshots.

A session is a state machine over its group slot:

    version v ──ask──> pending epoch (B candidate rows, decoded once)
        │                 │ tickets: one per UNIQUE config (in-epoch
        │                 │ duplicate rows share a ticket and a value)
        │                 │ store memo: rows another tenant already
        │                 │ measured are auto-filled — no ticket at all
        │<───commit────── │ every row filled -> publish version v+1

``ask`` returns tickets against the CURRENT version; ``tell`` fills
rows; the tell that completes the batch commits (one donated dispatch)
and publishes the next version.  A ticket from a published-over epoch
is stale and rejected (StaleTicketError) — the versioned-snapshot
contract of the PR 5 surrogate plane, applied to tenants.

``LocalSession`` is the same machinery on a private single-slot group:
the *offline tuner* of the serving plane.  The parity tests (and the
bench's sequential baseline) hold the multiplexed server to bitwise
per-session equality with it at matched seeds.
"""
from __future__ import annotations

import uuid
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from .. import obs
from ..obs.quality import SessionQuality
from ..store.keys import canon_config


class StaleTicketError(KeyError):
    """tell() against a ticket that is unknown, already told, or from
    an epoch that has been published over."""


class TrialOffer(NamedTuple):
    """One ask() result row: measure `config` and tell `ticket` its
    QoR.  (`cached` offers carry a store-served QoR and need no tell —
    the serving counters report them; ask() returns only live
    tickets.)"""
    ticket: int
    config: Dict[str, Any]


class _Pending(object):
    """One epoch's host-side bookkeeping.  All accounting is LAZY:
    rows are scanned, canon-deduped and memo-checked only as ask()
    hands tickets out, so every request costs O(rows touched this
    call), never O(B) — the serving plane's tail-latency contract
    (an eager per-epoch pass put milliseconds of sha1/decode work
    under the group lock on EVERY epoch-boundary ask, which is
    exactly what BENCH_SERVE's ask p95 would have caught).

    In-epoch dedup: rows with one canonical config share one ticket
    and one measured value (the engine's own dedup would classify
    them as duplicates anyway; a tenant should never be asked to
    build the same config twice in one batch)."""

    __slots__ = ("epoch", "version", "configs", "raw", "filled",
                 "next_row", "by_canon", "group_rows", "group_value",
                 "tickets")

    def __init__(self, epoch, version: int, configs: List[dict]):
        self.epoch = epoch
        self.version = version
        self.configs = configs
        b = len(configs)
        self.raw = np.full((b,), np.nan, np.float32)
        self.filled = np.zeros((b,), bool)
        self.next_row = 0                       # lazy scan cursor
        self.by_canon: Dict[str, int] = {}      # canon -> dup-group
        self.group_rows: List[List[int]] = []
        self.group_value: List[Optional[float]] = []
        self.tickets: Dict[int, int] = {}       # ticket id -> dup-group

    def fill(self, g: int, value: float) -> None:
        rows = self.group_rows[g]
        self.raw[rows] = value
        self.filled[rows] = True

    @property
    def unfilled(self) -> int:
        return int((~self.filled).sum())

    def settled(self) -> bool:
        """Every row scanned, no ticket outstanding, every row filled
        -> ready to commit."""
        return (self.next_row >= len(self.configs)
                and not self.tickets and self.unfilled == 0)


class Session:
    """One tenant bound to one group slot.  All methods take the
    group's lock; everything host-visible (incumbent, counters) lives
    here so `best` never touches the device."""

    def __init__(self, group, slot: int, seed: int, *,
                 store=None, session_id: Optional[str] = None):
        self.group = group
        self.slot = slot
        self.seed = seed
        self.id = session_id or uuid.uuid4().hex[:16]
        self.store = store
        self.version = 0            # published snapshots (commits)
        self.pending: Optional[_Pending] = None
        self.best_config: Optional[dict] = None
        self.best_qor: Optional[float] = None
        self.asks = 0
        self.tells = 0
        self.store_served = 0       # rows auto-filled from the memo
        self.closed = False
        self._ticket_seq = 0
        # per-tenant search-quality accumulator (ISSUE 12): a few ints
        # + one bounded ring, updated at tell time under the group
        # lock, read by the server's {"op": "health"} op — always on
        self.quality = SessionQuality()

    # -- internals -----------------------------------------------------
    def _offer_best(self, cfg: dict, qor: float) -> bool:
        sign = self.group.engine.sign
        if self.best_qor is None or sign * qor < sign * self.best_qor:
            self.best_config, self.best_qor = cfg, float(qor)
            obs.count("serve.new_bests")
            return True
        return False

    def _new_pending(self) -> Optional[_Pending]:
        """Build this session's pending epoch.  The group lock is NOT
        held across the expensive host side — epoch materialization
        (one stacked device->host pull) and config decode — so other
        tenants' asks and tells proceed under it.  Returns None when
        the epoch went stale between taking it and locking back in
        (this session committed concurrently — only possible with
        multiple clients driving one session); the ask loop then
        retries."""
        ep = self.group.pending_for(self)
        configs = self.group.space.to_configs(ep.host_rows(self.slot))
        with self.group.lock:
            if ep.slot_gens[self.slot] != self.group.slot_gen[self.slot] \
                    or self.pending is not None:
                return self.pending
            return self._adopt(ep, configs)

    def _adopt(self, ep, configs: List[dict]) -> _Pending:
        # memo/dedup accounting is deferred to ask()'s lazy row scan
        p = _Pending(ep, self.version, configs)
        self.pending = p
        return p

    def _scan_row(self, p: _Pending) -> Optional[TrialOffer]:
        """Advance the lazy cursor one row: attach duplicates to their
        group, auto-fill rows the cross-tenant memo already knows (any
        config ANY tenant of this scope measured is served without a
        build — and without a ticket), or mint a ticket.  Returns the
        offer for live rows, None otherwise."""
        r = p.next_row
        p.next_row += 1
        cfg = p.configs[r]
        c = canon_config(cfg)
        g = p.by_canon.get(c)
        if g is not None:                   # in-epoch duplicate
            p.group_rows[g].append(r)
            v = p.group_value[g]
            if v is not None:               # group already resolved
                p.raw[r] = v
                p.filled[r] = True
            return None                     # else: fills at its tell
        g = len(p.group_rows)
        p.by_canon[c] = g
        p.group_rows.append([r])
        row = self.store.lookup(cfg) if self.store is not None else None
        if row is not None:
            q = float(row["qor"])
            p.group_value.append(q)
            p.raw[r] = q
            p.filled[r] = True
            self.store_served += 1
            obs.count("serve.store_served")
            self._offer_best(cfg, q)
            return None
        p.group_value.append(None)
        t = self._ticket_seq
        self._ticket_seq += 1
        p.tickets[t] = g
        return TrialOffer(t, cfg)

    def _commit(self) -> None:
        p = self.pending
        self.group.commit(self, p.epoch, p.raw)
        self.version += 1
        self.pending = None

    # -- the ask/tell surface ------------------------------------------
    def ask(self, n: int = 1, max_auto: int = 4) -> List[TrialOffer]:
        """Up to `n` trial offers from the current epoch.  Epochs fully
        served by the store memo are committed and skipped (bounded by
        `max_auto` per call); fewer than `n` offers — possibly none —
        come back when the epoch's remaining rows are already ticketed
        out (tell those first).  An epoch refresh only ENQUEUES device
        work under the group lock (group.pending_for); the blocking
        host pull + config decode run unlocked (_new_pending)."""
        out: List[TrialOffer] = []
        autos = 0
        while not out:
            with self.group.lock:
                self._check_open()
                p = self.pending
            if p is None:
                p = self._new_pending()
                if p is None:
                    continue        # raced a concurrent driver; retry
            with self.group.lock:
                if self.pending is not p:
                    continue        # committed under us; take the next
                while p.next_row < len(p.configs) and len(out) < n:
                    offer = self._scan_row(p)
                    if offer is not None:
                        out.append(offer)
                if out:
                    self.asks += len(out)
                    break
                if p.settled():
                    # every row memo-served: publish and move on
                    self._commit()
                    autos += 1
                    if autos >= max_auto:
                        break
                    continue
                break   # remaining rows already ticketed: tell first
        obs.count("serve.asks", len(out))
        return out

    def tell(self, ticket: int, qor: Optional[float],
             dur: float = 0.0) -> Dict[str, Any]:
        """Report a ticket's USER-oriented QoR (None/NaN/inf = build
        failure).  The tell completing the epoch publishes the next
        snapshot version."""
        with self.group.lock:
            self._check_open()
            p = self.pending
            if p is None or ticket not in p.tickets:
                raise StaleTicketError(
                    f"ticket {ticket} is unknown, already told, or "
                    f"from a published-over epoch (session "
                    f"{self.id}, version {self.version})")
            # convert BEFORE popping: a malformed qor (string, list)
            # must leave the ticket live for a retry, not consume it
            # and strand the epoch one row short of settled forever
            v = float("nan") if qor is None else float(qor)
            g = p.tickets.pop(ticket)
            finite = v == v and abs(v) != float("inf")
            p.group_value[g] = v if finite else float("nan")
            p.fill(g, p.group_value[g])
            cfg = p.configs[p.group_rows[g][0]]
            new_best = False
            if finite:
                new_best = self._offer_best(cfg, v)
            self.tells += 1
            self.quality.on_tell(finite, new_best)
            committed = False
            if p.settled():
                self._commit()
                committed = True
            version = self.version
        if obs.journal.enabled():
            # the server-side tuning journal (per-tenant stream): one
            # row per committed tell, so `ut report` over a server's
            # journal shows each session's progress and the health op's
            # verdicts are reconstructible offline (ISSUE 12)
            obs.journal.emit(
                "serve_tell", session=self.id, ok=finite,
                qor=round(v, 6) if finite else None,
                new_best=new_best, committed=committed,
                version=version)
        # the memo write happens OUTSIDE the group lock (the store has
        # its own lock; a racing reader either hits or re-measures —
        # never a correctness matter), keeping disk appends off the
        # group's serving path.  Best-effort to the end: the tell is
        # already applied above, so a failed append (disk full, store
        # closed by a racing stop) must not fail the response — that
        # would report ok=False for an epoch that really committed
        if self.store is not None:
            try:
                self.store.record(cfg, v if finite else None, dur,
                                  source=f"serve:{self.id}")
            except OSError:
                obs.count("serve.store_write_errors")
        obs.count("serve.tells")
        return {"new_best": new_best, "committed": committed,
                "version": version}

    def best(self) -> Dict[str, Any]:
        """Host-side incumbent (never a device sync)."""
        with self.group.lock:
            return {"config": self.best_config, "qor": self.best_qor,
                    "version": self.version, "asks": self.asks,
                    "tells": self.tells,
                    "store_served": self.store_served}

    def health(self, *, stall_tells: int = 64,
               fail_rate_hi: float = 0.5) -> Dict[str, Any]:
        """Per-session quality verdict (never a device sync): the
        SessionQuality status plus the counters a poller needs to act
        on it — the serve `{"op": "health"}` payload."""
        with self.group.lock:
            out = {"session": self.id, "version": self.version,
                   "asks": self.asks, "store_served": self.store_served,
                   "best_qor": self.best_qor}
            out.update(self.quality.health(stall_tells=stall_tells,
                                           fail_rate_hi=fail_rate_hi))
            return out

    def close(self) -> None:
        with self.group.lock:
            if not self.closed:
                self.closed = True
                self.pending = None
                self.group.leave(self)

    def _check_open(self) -> None:
        if self.closed:
            raise StaleTicketError(f"session {self.id} is closed")

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LocalSession:
    """The offline sibling: identical session mechanics on a private
    single-slot group, no server, no sockets.

        with LocalSession(space, seed=3) as s:
            while budget:
                for t in s.ask(8):
                    s.tell(t.ticket, measure(t.config))
        s.best()

    Matched seeds make this bitwise equal to a server session — the
    parity bar tests/test_serve.py holds the multiplexed plane to —
    and it is the bench's sequential per-session baseline."""

    def __init__(self, space, seed: int = 0, *,
                 arms: Optional[Sequence[str]] = None,
                 sense: str = "min", history_capacity: int = 1 << 10,
                 store=None):
        from .group import SessionGroup
        self._group = SessionGroup(space, 1, arms=arms, sense=sense,
                                   history_capacity=history_capacity)
        self._session = self._group.join(seed, store=store)

    def ask(self, n: int = 1, **kw) -> List[TrialOffer]:
        return self._session.ask(n, **kw)

    def tell(self, ticket: int, qor: Optional[float],
             dur: float = 0.0) -> Dict[str, Any]:
        return self._session.tell(ticket, qor, dur)

    def best(self) -> Dict[str, Any]:
        return self._session.best()

    def health(self, **kw) -> Dict[str, Any]:
        return self._session.health(**kw)

    @property
    def version(self) -> int:
        return self._session.version

    def close(self) -> None:
        self._session.close()

    def __enter__(self) -> "LocalSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
