"""One tenant's session: tickets over versioned snapshots.

A session is a state machine over its group slot:

    version v ──ask──> pending epoch (B candidate rows, decoded once)
        │                 │ tickets: one per UNIQUE config (in-epoch
        │                 │ duplicate rows share a ticket and a value)
        │                 │ store memo: rows another tenant already
        │                 │ measured are auto-filled — no ticket at all
        │<───commit────── │ every row filled -> publish version v+1

``ask`` returns tickets against the CURRENT version; ``tell`` fills
rows; the tell that completes the batch commits (one donated dispatch)
and publishes the next version.  A ticket from a published-over epoch
is stale and rejected (StaleTicketError) — the versioned-snapshot
contract of the PR 5 surrogate plane, applied to tenants.

``LocalSession`` is the same machinery on a private single-slot group:
the *offline tuner* of the serving plane.  The parity tests (and the
bench's sequential baseline) hold the multiplexed server to bitwise
per-session equality with it at matched seeds.
"""
from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from .. import obs
from ..obs.quality import SessionQuality
from ..store.keys import canon_config
from .durable import encode_raw


class StaleTicketError(KeyError):
    """tell() against a ticket that is unknown, already told, or from
    an epoch that has been published over."""


class SessionRestoredError(StaleTicketError):
    """tell() against a ticket from an in-flight epoch that a server
    crash destroyed: the session was restored from its checkpoint
    (durable.py) and the epoch's ticket assignment cannot be trusted
    across the restart — the client must re-ask (reissue) and retry
    with the fresh tickets (docs/SERVING.md "Durability & failover")."""


class TrialOffer(NamedTuple):
    """One ask() result row: measure `config` and tell `ticket` its
    QoR.  (`cached` offers carry a store-served QoR and need no tell —
    the serving counters report them; ask() returns only live
    tickets.)  `epoch` is the session version the ticket was issued
    against — carried back by resuming clients so a duplicate tell
    replay is detected server-side (ISSUE 15).  `canon` is the
    config's canonical JSON text, computed once per epoch for the
    dedup scan and reused by the server's preserialized ask reply
    (ISSUE 20) — None for offers minted off paths that never
    canonicalized (LocalSession callers ignore it)."""
    ticket: int
    config: Dict[str, Any]
    epoch: int = 0
    canon: Optional[str] = None


class _Pending(object):
    """One epoch's host-side bookkeeping.  All accounting is LAZY:
    rows are scanned, canon-deduped and memo-checked only as ask()
    hands tickets out, so every request costs O(rows touched this
    call), never O(B) — the serving plane's tail-latency contract
    (an eager per-epoch pass put milliseconds of sha1/decode work
    under the group lock on EVERY epoch-boundary ask, which is
    exactly what BENCH_SERVE's ask p95 would have caught).

    In-epoch dedup: rows with one canonical config share one ticket
    and one measured value (the engine's own dedup would classify
    them as duplicates anyway; a tenant should never be asked to
    build the same config twice in one batch)."""

    __slots__ = ("epoch", "version", "configs", "raw", "filled",
                 "next_row", "by_canon", "group_canon", "group_rows",
                 "group_value", "tickets", "told")

    def __init__(self, epoch, version: int, configs: List[dict]):
        self.epoch = epoch
        self.version = version
        self.configs = configs
        b = len(configs)
        self.raw = np.full((b,), np.nan, np.float32)
        self.filled = np.zeros((b,), bool)
        self.next_row = 0                       # lazy scan cursor
        self.by_canon: Dict[str, int] = {}      # canon -> dup-group
        self.group_canon: List[str] = []        # dup-group -> canon
        self.group_rows: List[List[int]] = []
        self.group_value: List[Optional[float]] = []
        self.tickets: Dict[int, int] = {}       # ticket id -> dup-group
        self.told: set = set()     # consumed tickets (duplicate squash)

    def fill(self, g: int, value: float) -> None:
        rows = self.group_rows[g]
        self.raw[rows] = value
        self.filled[rows] = True

    @property
    def unfilled(self) -> int:
        return int((~self.filled).sum())

    def settled(self) -> bool:
        """Every row scanned, no ticket outstanding, every row filled
        -> ready to commit."""
        return (self.next_row >= len(self.configs)
                and not self.tickets and self.unfilled == 0)


class Session:
    """One tenant bound to one group slot.  All methods take the
    group's lock; everything host-visible (incumbent, counters) lives
    here so `best` never touches the device."""

    def __init__(self, group, slot: int, seed: int, *,
                 store=None, session_id: Optional[str] = None):
        self.group = group
        self.slot = slot
        self.seed = seed
        self.id = session_id or uuid.uuid4().hex[:16]
        self.store = store
        self.version = 0            # published snapshots (commits)
        self.pending: Optional[_Pending] = None
        self.best_config: Optional[dict] = None
        self.best_qor: Optional[float] = None
        self.asks = 0
        self.tells = 0
        self.store_served = 0       # rows auto-filled from the memo
        self.closed = False
        self._ticket_seq = 0
        # durable checkpoint plane (ISSUE 15): the durable server sets
        # `durable` to its CheckpointLog; _commit then buffers one
        # record per published version, drained OUTSIDE the group lock
        # but BEFORE the op's reply (ack-after-durable).  `incarn` is
        # the restart-incarnation token: bumped by crash recovery so a
        # pre-crash ticket can never be misapplied to a post-restore
        # epoch that happens to reuse its (version, ticket id) pair
        self.durable = None
        self._ckpt_buf: List[Dict[str, Any]] = []
        # serializes this session's checkpoint appends across handler
        # threads, and carries the flushed-version watermark they
        # synchronize on (see _drain_ckpt)
        self._ckpt_lock = threading.Lock()
        self._ckpt_flushed = 0
        self.incarn = "0"
        # per-tenant search-quality accumulator (ISSUE 12): a few ints
        # + one bounded ring, updated at tell time under the group
        # lock, read by the server's {"op": "health"} op — always on
        self.quality = SessionQuality()

    # -- internals -----------------------------------------------------
    def _offer_best(self, cfg: dict, qor: float) -> bool:
        sign = self.group.engine.sign
        if self.best_qor is None or sign * qor < sign * self.best_qor:
            self.best_config, self.best_qor = cfg, float(qor)
            obs.count("serve.new_bests")
            return True
        return False

    def _new_pending(self) -> Optional[_Pending]:
        """Build this session's pending epoch.  The group lock is NOT
        held across the expensive host side — epoch materialization
        (one stacked device->host pull) and config decode — so other
        tenants' asks and tells proceed under it.  Returns None when
        the epoch went stale between taking it and locking back in
        (this session committed concurrently — only possible with
        multiple clients driving one session); the ask loop then
        retries."""
        ep = self.group.pending_for(self)
        configs = self.group.space.to_configs(ep.host_rows(self.slot))
        with self.group.lock:
            if ep.slot_gens[self.slot] != self.group.slot_gen[self.slot] \
                    or self.pending is not None:
                return self.pending
            return self._adopt(ep, configs)

    def _adopt(self, ep, configs: List[dict]) -> _Pending:
        # memo/dedup accounting is deferred to ask()'s lazy row scan
        p = _Pending(ep, self.version, configs)
        self.pending = p
        return p

    def _scan_row(self, p: _Pending) -> Optional[TrialOffer]:
        """Advance the lazy cursor one row: attach duplicates to their
        group, auto-fill rows the cross-tenant memo already knows (any
        config ANY tenant of this scope measured is served without a
        build — and without a ticket), or mint a ticket.  Returns the
        offer for live rows, None otherwise."""
        r = p.next_row
        p.next_row += 1
        cfg = p.configs[r]
        c = canon_config(cfg)
        g = p.by_canon.get(c)
        if g is not None:                   # in-epoch duplicate
            p.group_rows[g].append(r)
            v = p.group_value[g]
            if v is not None:               # group already resolved
                p.raw[r] = v
                p.filled[r] = True
            return None                     # else: fills at its tell
        g = len(p.group_rows)
        p.by_canon[c] = g
        p.group_canon.append(c)
        p.group_rows.append([r])
        row = self.store.lookup(cfg) if self.store is not None else None
        if row is not None:
            q = float(row["qor"])
            p.group_value.append(q)
            p.raw[r] = q
            p.filled[r] = True
            self.store_served += 1
            obs.count("serve.store_served")
            self._offer_best(cfg, q)
            return None
        p.group_value.append(None)
        t = self._ticket_seq
        self._ticket_seq += 1
        p.tickets[t] = g
        return TrialOffer(t, cfg, p.version, c)

    def _commit(self) -> None:
        p = self.pending
        self.group.commit(self, p.epoch, p.raw)
        self.version += 1
        self.pending = None
        if self.durable is not None:
            # the v -> v+1 delta, buffered under the group lock (host
            # dict + one B-float list) and appended to disk by
            # _drain_ckpt outside it.  Incumbent/counters/quality are
            # checkpointed verbatim: replay preserves values but not
            # tell ORDER, and order is what breaks qor ties
            self._ckpt_buf.append({
                "ev": "commit", "v": self.version,
                "raw": encode_raw(p.raw),
                "best_cfg": self.best_config,
                "best_qor": self.best_qor,
                "asks": self.asks, "tells": self.tells,
                "served": self.store_served,
                "tseq": self._ticket_seq,
                "q": self.quality.state()})

    def _drain_ckpt(self) -> None:
        """Flush buffered commit records to the checkpoint segment —
        called outside the group lock (disk stays off the serving
        path) but before the op's reply is written, so a committed:
        true a client observed is always durable (the bounded-loss
        contract bench.py --failover prices).

        Two clients may drive one session from two handler threads,
        so the drain is serialized per session (_ckpt_lock) and the
        buffer swap happens INSIDE that lock: an op may only skip the
        drain when the flushed watermark already covers every version
        it could have published — never because a peer swapped the
        buffer but has not finished appending (acking v+1 while v sat
        un-appended in a stalled peer would leave a version gap scan()
        rightly refuses to replay past)."""
        if self.durable is None:
            return
        # racy fast path, safe by monotonicity: _ckpt_flushed only
        # grows (under _ckpt_lock), and self.version was published
        # before this op's commit record entered the buffer — a stale
        # read can only send us through the lock unnecessarily
        if self._ckpt_flushed >= self.version:
            return
        with self._ckpt_lock:
            with self.group.lock:
                recs, self._ckpt_buf = self._ckpt_buf, []
            for i, r in enumerate(recs):
                if not self.durable.append(self.id, r):
                    # disk refused (ENOSPC/EIO — counted by the log):
                    # requeue THIS record and the rest at the buffer
                    # FRONT, order preserved, so a later drain retries
                    # once the disk recovers.  The watermark must not
                    # advance past a hole — recovery truncates at the
                    # first version gap, so a skipped record would
                    # silently void every later acked commit
                    with self.group.lock:
                        self._ckpt_buf[:0] = recs[i:]
                    return
                self._ckpt_flushed = max(self._ckpt_flushed,
                                         int(r.get("v", 0)))

    # -- the ask/tell surface ------------------------------------------
    def ask(self, n: int = 1, max_auto: int = 4) -> List[TrialOffer]:
        """Up to `n` trial offers from the current epoch.  Epochs fully
        served by the store memo are committed and skipped (bounded by
        `max_auto` per call); fewer than `n` offers — possibly none —
        come back when the epoch's remaining rows are already ticketed
        out (tell those first).  An epoch refresh only ENQUEUES device
        work under the group lock (group.pending_for); the blocking
        host pull + config decode run unlocked (_new_pending).

        The fast path — k tickets off an already-materialized epoch —
        is ONE group-lock hold (ISSUE 20): open-check, row scan and
        ticket mint happen in the same acquisition, so a k-wide ask
        costs one lock round instead of k."""
        out: List[TrialOffer] = []
        autos = 0
        while not out:
            need_epoch = False
            with self.group.lock:
                self._check_open()
                p = self.pending
                if p is None:
                    need_epoch = True
                else:
                    while p.next_row < len(p.configs) and len(out) < n:
                        offer = self._scan_row(p)
                        if offer is not None:
                            out.append(offer)
                    if out:
                        self.asks += len(out)
                        break
                    if p.settled():
                        # every row memo-served: publish and move on
                        self._commit()
                        autos += 1
                        if autos >= max_auto:
                            break
                        continue
                    break   # rows already ticketed out: tell first
            if need_epoch:
                # the expensive host side (device pull + config
                # decode) runs UNLOCKED; the next loop pass re-reads
                # self.pending under the lock, so a concurrent commit
                # between here and there is simply retried
                self._new_pending()
        obs.count("serve.asks", len(out))
        # memo auto-commits above published versions: durable-ack them
        # before this ask's reply, same rule as the tell path
        self._drain_ckpt()
        return out

    def outstanding(self) -> List[TrialOffer]:
        """The current epoch's live (unanswered) tickets, re-offered
        in issue order — the reconnect path: an ask whose reply was
        lost already ticketed rows out, and re-asking must surface
        THOSE tickets or the epoch can never settle (the client
        resume protocol, docs/SERVING.md)."""
        with self.group.lock:
            p = self.pending
            if p is None:
                return []
            return [TrialOffer(t, p.configs[p.group_rows[g][0]],
                               p.version, p.group_canon[g])
                    for t, g in sorted(p.tickets.items())]

    def _squash_duplicate(self, p: Optional[_Pending], ticket: int,
                          epoch, incarn) -> Optional[Dict[str, Any]]:
        """Duplicate-replay detection (called under the group lock
        when `ticket` is not live).  A resuming client retries a tell
        whose reply it never observed; the ticket's epoch id tells
        the two cases apart: already-committed epoch -> squash as a
        durable duplicate; already-told but uncommitted -> squash
        without commit.  A ticket carrying a STALE incarnation token
        from before a crash-restore is only squashable when its epoch
        committed durably — otherwise it belongs to the lost
        in-flight epoch and the client must re-ask."""
        if epoch is None:
            return None
        try:
            epoch = int(epoch)
        except (TypeError, ValueError):
            return None
        if epoch < self.version:
            # the ticket's epoch published durably (commit records are
            # acked-before-reply): whatever incarnation issued it, its
            # tell is inside that commit — a pure duplicate
            return {"new_best": False, "committed": True,
                    "version": self.version, "duplicate": True}
        if incarn is not None and str(incarn) != self.incarn:
            raise SessionRestoredError(
                f"session {self.id} was restored after a crash; "
                f"ticket {ticket} belongs to a lost in-flight epoch "
                f"— re-ask (reissue) and retry")
        if p is not None and epoch == p.version and ticket in p.told:
            return {"new_best": False, "committed": False,
                    "version": self.version, "duplicate": True}
        return None

    def _tell_locked(self, ticket: int, qor, epoch, incarn):
        """Apply ONE tell under the group lock (caller holds it).
        Returns ``(result, fx)`` where ``fx`` is None for a squashed
        duplicate, else the ``(cfg, value, finite, new_best,
        committed)`` tuple the caller's unlocked side effects (journal
        row, store memo write) need.  Raises StaleTicketError /
        conversion errors exactly like the historical tell body —
        batch callers turn those into per-element error entries."""
        self._check_open()
        p = self.pending
        # a ticket carrying a stale incarnation token must NEVER
        # apply, even if its id coincides with a live ticket (the
        # restored id space is offset — _mark_restored — so this
        # is a belt, not the wall)
        stale_inc = (incarn is not None
                     and str(incarn) != self.incarn)
        if p is None or ticket not in p.tickets or stale_inc:
            dup = self._squash_duplicate(p, ticket, epoch, incarn)
            if dup is not None:
                obs.count("serve.dup_tells")
                return dup, None
            raise StaleTicketError(
                f"ticket {ticket} is unknown, already told, or "
                f"from a published-over epoch (session "
                f"{self.id}, version {self.version})")
        # convert BEFORE popping: a malformed qor (string, list)
        # must leave the ticket live for a retry, not consume it
        # and strand the epoch one row short of settled forever
        v = float("nan") if qor is None else float(qor)
        g = p.tickets.pop(ticket)
        p.told.add(ticket)
        finite = v == v and abs(v) != float("inf")
        p.group_value[g] = v if finite else float("nan")
        p.fill(g, p.group_value[g])
        cfg = p.configs[p.group_rows[g][0]]
        new_best = False
        if finite:
            new_best = self._offer_best(cfg, v)
        self.tells += 1
        self.quality.on_tell(finite, new_best)
        committed = False
        if p.settled():
            self._commit()
            committed = True
        return ({"new_best": new_best, "committed": committed,
                 "version": self.version},
                (cfg, v, finite, new_best, committed, self.version))

    def _tell_fx(self, fx, dur: float) -> None:
        """One applied tell's unlocked side effects: the journal row
        and the cross-tenant memo write — disk stays off the group's
        serving path."""
        cfg, v, finite, new_best, committed, version = fx
        if obs.journal.enabled():
            # the server-side tuning journal (per-tenant stream): one
            # row per committed tell, so `ut report` over a server's
            # journal shows each session's progress and the health op's
            # verdicts are reconstructible offline (ISSUE 12)
            obs.journal.emit(
                "serve_tell", session=self.id, ok=finite,
                qor=round(v, 6) if finite else None,
                new_best=new_best, committed=committed,
                version=version)
        # the memo write happens OUTSIDE the group lock (the store has
        # its own lock; a racing reader either hits or re-measures —
        # never a correctness matter).  Best-effort to the end: the
        # tell is already applied, so a failed append (disk full,
        # store closed by a racing stop) must not fail the response —
        # that would report ok=False for an epoch that really
        # committed
        if self.store is not None:
            try:
                self.store.record(cfg, v if finite else None, dur,
                                  source=f"serve:{self.id}")
            except OSError:
                obs.count("serve.store_write_errors")
        obs.count("serve.tells")

    def tell(self, ticket: int, qor: Optional[float],
             dur: float = 0.0, epoch=None, incarn=None
             ) -> Dict[str, Any]:
        """Report a ticket's USER-oriented QoR (None/NaN/inf = build
        failure).  The tell completing the epoch publishes the next
        snapshot version.  `epoch`/`incarn` are the resume protocol's
        idempotence tags (the ticket's TrialOffer.epoch and the ask
        reply's incarnation token): a duplicate replay after an
        acked-but-unobserved reply is detected and squashed instead
        of raising or double-applying."""
        with self.group.lock:
            out, fx = self._tell_locked(ticket, qor, epoch, incarn)
        # durable-before-ack: the commit record (if this tell
        # published) hits disk before this method returns a
        # committed=true the client could act on
        self._drain_ckpt()
        if fx is not None:
            self._tell_fx(fx, dur)
        return out

    def tell_many(self, rows: Sequence[Any], incarn=None
                  ) -> Dict[str, Any]:
        """Apply a batch of tells in ONE group-lock hold and ack them
        all behind ONE checkpoint drain (ISSUE 20) — the vectorized
        server op.  Each row is a ``{"ticket", "qor"[, "dur",
        "epoch"]}`` object carrying its own epoch tag; `incarn` covers
        the batch (one client, one incarnation).  Element-wise error
        walls: a stale/malformed row becomes an ``errors`` entry and
        the rest still apply — exactly the PR 15 duplicate-squash
        matrix, row by row.  Ack-after-durable holds batch-wide: the
        single ``_drain_ckpt`` below flushes EVERY version this batch
        published before the one reply that acks it."""
        out: Dict[str, Any] = {"told": 0, "new_best": False,
                               "committed": False, "duplicates": 0,
                               "version": self.version}
        errors: List[Dict[str, Any]] = []
        fxs: List[Any] = []
        with self.group.lock:
            for r in rows:
                try:
                    # convert dur (and ticket) BEFORE applying, so a
                    # malformed row leaves its ticket live for retry
                    dur = float(r.get("dur") or 0.0)
                    one, fx = self._tell_locked(
                        int(r["ticket"]), r.get("qor"),
                        r.get("epoch"), incarn)
                except StaleTicketError as e:
                    errors.append({"ticket": r.get("ticket"),
                                   "error": str(e)})
                    continue
                except (KeyError, TypeError, ValueError,
                        AttributeError) as e:
                    errors.append({"ticket": (r.get("ticket")
                                              if isinstance(r, dict)
                                              else None),
                                   "error": f"bad tell payload: {e}"})
                    continue
                if one.get("duplicate"):
                    out["duplicates"] += 1
                else:
                    out["told"] += 1
                    out["new_best"] = (out["new_best"]
                                       or one["new_best"])
                    fxs.append((fx, dur))
                out["committed"] = out["committed"] or one["committed"]
                out["version"] = one["version"]
        if errors:
            out["errors"] = errors
        # ONE durable drain acks the whole batch (every commit this
        # batch buffered is on disk before the reply), then the
        # unlocked per-tell side effects in application order
        self._drain_ckpt()
        for fx, dur in fxs:
            self._tell_fx(fx, dur)
        return out

    def best(self) -> Dict[str, Any]:
        """Host-side incumbent (never a device sync)."""
        with self.group.lock:
            return {"config": self.best_config, "qor": self.best_qor,
                    "version": self.version, "asks": self.asks,
                    "tells": self.tells,
                    "store_served": self.store_served}

    def health(self, *, stall_tells: int = 64,
               fail_rate_hi: float = 0.5) -> Dict[str, Any]:
        """Per-session quality verdict (never a device sync): the
        SessionQuality status plus the counters a poller needs to act
        on it — the serve `{"op": "health"}` payload."""
        with self.group.lock:
            out = {"session": self.id, "version": self.version,
                   "asks": self.asks, "store_served": self.store_served,
                   "best_qor": self.best_qor}
            out.update(self.quality.health(stall_tells=stall_tells,
                                           fail_rate_hi=fail_rate_hi))
            return out

    # -- crash recovery (serve/durable.py) -----------------------------
    def _replay_commit(self, raw: Sequence[float]) -> None:
        """Re-publish one committed epoch through the SAME compiled
        propose/commit programs — no tickets, no config decode: the
        stream of raw batches alone determines the device state, and
        `propose` is pure in the state, so the replayed session is
        bitwise identical to one that never died."""
        with self.group.lock:
            ep = self.group.pending_for(self)
            self.group.commit(self, ep, np.asarray(raw, np.float32))
            self.version += 1
            self.pending = None

    def _mark_restored(self, incarn: str) -> None:
        """Stamp a crash-restored session: a fresh incarnation token
        (pre-crash tickets are detected, squashed or rejected — never
        misapplied) and a ticket-id space offset past anything the
        lost incarnation could have minted (ids are wire handles, not
        device state, so the offset never touches parity)."""
        with self.group.lock:
            self.incarn = str(incarn)
            self._ticket_seq += 1 << 20
            # every replayed version came FROM the segment: durable
            self._ckpt_flushed = self.version

    def _restore_host(self, rec: Dict[str, Any], incarn: str) -> None:
        """Host-side accounting from the last commit record —
        checkpointed verbatim because replay preserves values but not
        tell order, and order is what breaks qor ties."""
        with self.group.lock:
            self.best_config = rec.get("best_cfg")
            bq = rec.get("best_qor")
            self.best_qor = None if bq is None else float(bq)
            self.asks = int(rec.get("asks", 0))
            self.tells = int(rec.get("tells", 0))
            self.store_served = int(rec.get("served", 0))
            self._ticket_seq = int(rec.get("tseq", 0))
            q = rec.get("q")
            if q is not None:
                self.quality.restore(q)
        self._mark_restored(incarn)

    def close(self) -> None:
        with self.group.lock:
            if self.closed:
                return
            self.closed = True
            self.pending = None
            self.group.leave(self)
        # any not-yet-drained commit must land before the close mark,
        # then the segment is reaped (a recovering server also reaps
        # segments whose stream ends in a close record)
        self._drain_ckpt()
        if self.durable is not None:
            self.durable.append(self.id, {"ev": "close"})
            self.durable.reap(self.id)

    def _check_open(self) -> None:
        if self.closed:
            raise StaleTicketError(f"session {self.id} is closed")

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LocalSession:
    """The offline sibling: identical session mechanics on a private
    single-slot group, no server, no sockets.

        with LocalSession(space, seed=3) as s:
            while budget:
                for t in s.ask(8):
                    s.tell(t.ticket, measure(t.config))
        s.best()

    Matched seeds make this bitwise equal to a server session — the
    parity bar tests/test_serve.py holds the multiplexed plane to —
    and it is the bench's sequential per-session baseline."""

    def __init__(self, space, seed: int = 0, *,
                 arms: Optional[Sequence[str]] = None,
                 sense: str = "min", history_capacity: int = 1 << 10,
                 store=None):
        from .group import SessionGroup
        self._group = SessionGroup(space, 1, arms=arms, sense=sense,
                                   history_capacity=history_capacity)
        self._session = self._group.join(seed, store=store)

    def ask(self, n: int = 1, **kw) -> List[TrialOffer]:
        return self._session.ask(n, **kw)

    def tell(self, ticket: int, qor: Optional[float],
             dur: float = 0.0) -> Dict[str, Any]:
        return self._session.tell(ticket, qor, dur)

    def tell_many(self, rows: Sequence[Any]) -> Dict[str, Any]:
        return self._session.tell_many(rows)

    def best(self) -> Dict[str, Any]:
        return self._session.best()

    def health(self, **kw) -> Dict[str, Any]:
        return self._session.health(**kw)

    @property
    def version(self) -> int:
        return self._session.version

    def close(self) -> None:
        self._session.close()

    def __enter__(self) -> "LocalSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
