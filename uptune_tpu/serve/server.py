"""The session server: newline-delimited JSON over TCP, thread per
connection, every request one JSON object with an ``op`` field.

    {"op": "open", "space": [...param records...], "seed": 3,
     "program": "my-flow"}            -> {"ok": true, "session": "..."}
    {"op": "ask", "session": s, "n": 4}
    {"op": "tell", "session": s, "ticket": 0, "qor": 1.25}
    {"op": "best", "session": s}
    {"op": "close", "session": s}
    {"op": "metrics"}                 -> the obs metrics scrape
    {"op": "health", "session": s}    -> per-session search quality
    {"op": "stats"} / {"op": "ping"}

``SessionServer.handle(request) -> response`` is the transport-free
dispatch (tests and the in-process bench drive it directly); the TCP
layer is one reader/writer loop around it.  An optional ``id`` field
is echoed verbatim so clients may pipeline.  Since ISSUE 14 the
generic half — dispatch table, per-op error walls, the accept /
reader loops, connection reaping — lives in ``serve.wire.WireServer``
(shared with the fleet-telemetry hub, obs/hub.py); this module owns
only the session-plane ops and registries.

Tenant grouping happens at ``open``: the request's space records are
rebuilt into a Space, and sessions whose ``group_key`` matches share
one BatchedEngine instance axis (new groups are allocated when
existing ones fill).  Scoped result stores — the cross-tenant memo —
are shared per (space signature, program token) under one store
directory, so one tenant's recorded build serves another's ask.

There is no authentication or tenant quota beyond the session cap:
this is an in-cluster serving plane, not an internet-facing one
(docs/SERVING.md).
"""
from __future__ import annotations

import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .. import obs
from ..api.session import settings
from ..exec.space_io import space_from_params
from ..store import is_remote_addr, open_store
from ..store.store import ResultStore
from .durable import CheckpointLog, decode_raw, default_checkpoint_dir
from .group import SessionGroup, group_key
from .session import Session, StaleTicketError
from .wire import (RequestError, WireReply,  # noqa: F401  (re-export)
                   WireServer)
from .wire import _ENC as _enc

log = logging.getLogger("uptune_tpu")

# a client-proposed durable session id becomes a checkpoint FILENAME:
# constrain it to the uuid-hex shape the server mints itself
_SID_OK = re.compile(r"^[A-Za-z0-9_-]{1,64}$")


def _resolve(value, key):
    """The documented precedence: explicit argument (CLI flag layer) >
    ut.config session settings > DEFAULTS."""
    return settings[key] if value is None else value


class SessionServer(WireServer):
    """One serving process.  Construct, ``start()``, ``connect()``
    clients against ``.port``, ``stop()``.  All constructor parameters
    default through the ``serve-*`` ut.config keys."""

    WIRE_NAME = "ut-serve"

    # grace a disconnected durable tenant gets before its slot is
    # swept (seconds): a resuming client re-attaches well inside it,
    # a truly dead one stops leaking its slot + admission unit —
    # lazily enforced on open/attach/stats (no reaper thread)
    ORPHAN_TTL = 900.0

    def __init__(self, host: Optional[str] = None,
                 port: Optional[int] = None,
                 slots: Optional[int] = None,
                 max_sessions: Optional[int] = None,
                 store_dir: Optional[str] = None,
                 work_dir: Optional[str] = None,
                 durable: Optional[str] = None,
                 durable_fsync: Optional[bool] = None,
                 orphan_ttl: Optional[float] = None):
        super().__init__(str(_resolve(host, "serve-host")),
                         int(_resolve(port, "serve-port")))
        self.slots = int(_resolve(slots, "serve-slots"))
        self.max_sessions = int(_resolve(max_sessions,
                                         "serve-max-sessions"))
        if self.slots < 1:
            raise ValueError(f"serve-slots must be >= 1: {self.slots}")
        sd = _resolve(store_dir, "serve-store-dir")
        self.work_dir = os.path.abspath(work_dir or os.getcwd())
        if sd is None:
            sd = os.path.join(self.work_dir, "ut.serve", "store")
        # a tcp:// base joins a cooperative store server (ISSUE 18,
        # docs/STORE.md "Remote store") — an address, not a path
        self.store_dir = (None if str(sd).lower() in ("off", "none")
                          else str(sd) if is_remote_addr(sd)
                          else os.path.abspath(str(sd)))
        # self._lock (WireServer) guards the registries below too
        self._groups: Dict[Tuple, List[SessionGroup]] = {}
        self._glocks: Dict[Tuple, threading.Lock] = {}
        self._admitted = 0      # admission reservations (<= max)
        self._sessions: Dict[str, Session] = {}
        self._stores: Dict[Tuple, ResultStore] = {}
        # the metrics registry only records while the obs plane is
        # enabled; a serving process keeps it on so the scrape op (and
        # BENCH_SERVE's evidence) always has data.  Span rings are
        # bounded per thread, so long-lived servers don't grow
        if not obs.enabled():
            obs.enable()
        # -- durable sessions (ISSUE 15, docs/SERVING.md) --------------
        dv = _resolve(durable, "serve-durable")
        if dv is not None and str(dv).lower() in ("off", "none", "0",
                                                  "false"):
            dv = None
        self.ckpt: Optional[CheckpointLog] = None
        self.orphan_ttl = float(orphan_ttl if orphan_ttl is not None
                                else self.ORPHAN_TTL)
        self._orphans: Dict[str, float] = {}   # sid -> disconnect time
        # sid -> owning-connection token (id of its owned-set): a DEAD
        # connection may only orphan-stamp sessions it still owns, so
        # a lingering old connection's demise cannot re-orphan a
        # session its client already re-attached elsewhere
        self._owners: Dict[str, int] = {}
        self.recovered = 0
        self.recovery_s = 0.0
        if dv is not None:
            # a remote store base is no place for checkpoint files —
            # 'on' falls back to the work-dir default then
            local_sd = (None if is_remote_addr(self.store_dir)
                        else self.store_dir)
            cdir = (default_checkpoint_dir(local_sd, self.work_dir)
                    if str(dv).lower() in ("on", "true", "1")
                    else os.path.abspath(str(dv)))
            self.ckpt = CheckpointLog(
                cdir, fsync=bool(_resolve(durable_fsync,
                                          "serve-durable-fsync")))
            self._recover()

    # -- registry ------------------------------------------------------
    def _store_for(self, space, program: str) -> Optional[ResultStore]:
        if self.store_dir is None:
            return None
        sig = space.signature()
        key = (tuple(sig), str(program))
        with self._lock:
            st = self._stores.get(key)
        if st is not None:
            return st
        # construct OUTSIDE the registry lock (the initial base/seg
        # disk scan can be large — the _join_group rule: a new
        # tenant's construction wall must not stall every other op),
        # double-checked insert under it.  The eval signature is the
        # tenant-declared program token: tenants naming the same
        # program (and space) share rows; different tokens never
        # collide.  A losing racer's instance never touched disk
        # (the segment opens lazily on first append) — just close it.
        new = open_store(self.store_dir, sig,
                         ["ut-serve", str(program)])
        with self._lock:
            st = self._stores.get(key)
            if st is None:
                self._stores[key] = st = new
        if st is not new:
            new.close()
        return st

    def _join_group(self, space, arms, sense: str,
                    history_capacity: int, seed: int, store,
                    session_id: Optional[str] = None) -> Session:
        """Join a free slot in an existing group for this key, or
        construct a new group and join it.  Group construction traces
        and compiles three programs (seconds) — it runs under a PER-KEY
        construction lock, never the registry lock, so a new tenant's
        compile wall stalls only same-key joiners, not the rest of the
        serving plane."""
        key = group_key(space, arms, sense, history_capacity)
        with self._lock:
            klock = self._glocks.setdefault(key, threading.Lock())
        while True:
            with self._lock:
                frees = [g for g in self._groups.setdefault(key, [])
                         if g.n_free]
            for g in frees:
                try:
                    return g.join(seed, store=store,
                                  session_id=session_id)
                except IndexError:
                    continue    # lost the last slot to a racing join
            with klock:
                with self._lock:
                    if any(g.n_free for g in self._groups[key]):
                        continue    # a slot freed while we waited
                g = SessionGroup(space, self.slots, arms=arms,
                                 sense=sense,
                                 history_capacity=history_capacity)
                with self._lock:
                    self._groups[key].append(g)
                obs.count("serve.groups_created")

    # -- crash recovery (serve/durable.py, ISSUE 15) -------------------
    def _recover(self) -> None:
        """Restore every live checkpointed session (reaping closed
        ones) before the listener binds: a resuming client's attach
        can never observe a half-recovered registry.  Each restore
        replays the commit stream through the group's compiled
        propose/commit programs — signatures with more survivors than
        one group's slots simply allocate further groups, exactly as
        live opens do."""
        t0 = time.perf_counter()
        for sid, bundle in self.ckpt.scan():
            if bundle["closed"] or bundle["open"] is None:
                self.ckpt.reap(sid)
                continue
            try:
                self._restore_session(sid, bundle)
                self.recovered += 1
            except Exception:
                # one corrupt/unplaceable segment must not take down
                # every other tenant's recovery; the segment is kept
                # on disk for post-mortem
                log.exception("[%s] failed to restore session %s",
                              self.WIRE_NAME, sid)
                obs.count("serve.recover_errors")
        self.recovery_s = round(time.perf_counter() - t0, 3)
        if self.recovered:
            log.info("[%s] recovered %d session(s) in %.2fs from %s",
                     self.WIRE_NAME, self.recovered, self.recovery_s,
                     self.ckpt.root)
        obs.gauge("serve.recovered", self.recovered)

    def _restore_session(self, sid: str, bundle: dict) -> None:
        o = bundle["open"]
        space = space_from_params(o["space"])
        store = (self._store_for(space, str(o.get("program", "")))
                 if o.get("store") else None)
        with self._lock:
            if self._admitted >= self.max_sessions:
                raise RequestError(
                    f"server full ({self.max_sessions} sessions)")
            self._admitted += 1
        try:
            sess = self._join_group(
                space, o.get("arms"), str(o.get("sense", "min")),
                int(o.get("hist", 1 << 10)), int(o.get("seed", 0)),
                store, session_id=sid)
            for rec in bundle["commits"]:
                sess._replay_commit(decode_raw(rec["raw"]))
            if bundle["commits"]:
                sess._restore_host(bundle["commits"][-1],
                                   uuid.uuid4().hex[:8])
            else:
                sess._mark_restored(uuid.uuid4().hex[:8])
            sess.durable = self.ckpt
            with self._lock:
                self._sessions[sess.id] = sess
                # restored tenants start disconnected: the orphan
                # clock runs until their client re-attaches
                self._orphans[sess.id] = time.time()
                obs.gauge("serve.sessions.active", self.n_sessions)
        except BaseException:
            with self._lock:
                self._admitted -= 1
            raise

    def _sweep_orphans(self) -> None:
        """Close durable sessions whose client disconnected more than
        orphan_ttl ago (lazily, from the open/attach/stats paths):
        resume stays lossless inside the grace window, and a dead
        tenant stops pinning its slot + admission unit forever."""
        if self.ckpt is None or not self._orphans:
            return
        now = time.time()
        with self._lock:
            expired = [sid for sid, t in self._orphans.items()
                       if now - t > self.orphan_ttl]
            for sid in expired:
                self._orphans.pop(sid, None)
        for sid in expired:
            self.handle({"op": "close", "session": sid})
            obs.count("serve.orphans_reaped")

    def _session(self, req: dict) -> Session:
        sid = req.get("session")
        sess = self._sessions.get(sid)
        if sess is None:
            raise RequestError(f"unknown session {sid!r}")
        # activity cancels orphanhood: a recovered session driven
        # without an explicit attach (in-process callers, a client
        # whose attach was lost) must not be swept mid-drive.  One
        # truthy check on the hot path; the lock only when a clock is
        # actually running
        if self._orphans and self.ckpt is not None:
            with self._lock:
                self._orphans.pop(sess.id, None)
        return sess

    @property
    def n_sessions(self) -> int:
        return len(self._sessions)

    # -- ops -----------------------------------------------------------
    def _op_ping(self, req: dict) -> dict:
        return {"t": time.time(), "sessions": self.n_sessions}

    def _op_open(self, req: dict) -> dict:
        records = req.get("space")
        if not isinstance(records, list) or not records:
            raise RequestError("open needs 'space': a non-empty list "
                               "of param records")
        try:
            space = space_from_params(records)
        except (KeyError, TypeError, ValueError) as e:
            raise RequestError(f"bad space records: {e}")
        sense = req.get("sense", "min")
        if sense not in ("min", "max"):
            raise RequestError(f"sense must be min|max: {sense!r}")
        arms = req.get("arms")
        if arms is not None and not (
                isinstance(arms, list)
                and all(isinstance(a, str) for a in arms)):
            raise RequestError("arms must be a list of technique names")
        try:
            hist = int(req.get("history_capacity", 1 << 10))
            seed = int(req.get("seed", 0))
        except (TypeError, ValueError) as e:
            raise RequestError(
                f"history_capacity/seed must be integers: {e}")
        program = str(req.get("program", ""))
        use_store = str(req.get("store", "on")).lower() not in (
            "off", "false", "0")
        # a resuming client may propose its own durable session id so
        # a retried open (reply lost mid-exchange) re-attaches instead
        # of leaking a second session.  The id becomes a checkpoint
        # filename: constrain its shape
        sid = req.get("session")
        if sid is not None:
            if not isinstance(sid, str) or not _SID_OK.match(sid):
                raise RequestError(
                    "session id must match [A-Za-z0-9_-]{1,64}")
            with self._lock:
                existing = self._sessions.get(sid)
                if existing is not None:
                    # idempotent re-open = an attach: the resuming
                    # client is live again, so its orphan clock (a
                    # lost-reply disconnect may have started it) stops
                    self._orphans.pop(sid, None)
            if existing is not None:
                return self._attach_payload(existing)
        self._sweep_orphans()
        # admission is a reserve-then-join two-step so the (possibly
        # compiling) join runs outside the registry lock without
        # letting racing opens overshoot max_sessions
        with self._lock:
            if self._admitted >= self.max_sessions:
                raise RequestError(
                    f"server full ({self.max_sessions} sessions)")
            self._admitted += 1
        try:
            store = (self._store_for(space, program) if use_store
                     else None)
            try:
                sess = self._join_group(space, arms, sense, hist,
                                        seed, store, session_id=sid)
            except ValueError as e:     # e.g. no arm supports space
                raise RequestError(str(e))
            if self.ckpt is not None:
                # the open record is durable BEFORE the reply: a
                # session a client ever heard about is recoverable
                self.ckpt.append(sess.id, {
                    "ev": "open", "t": round(time.time(), 3),
                    "space": records, "seed": seed,
                    "program": program, "sense": sense, "arms": arms,
                    "hist": hist, "store": store is not None})
                sess.durable = self.ckpt
            with self._lock:
                cur = self._sessions.get(sess.id)
                if cur is None:
                    self._sessions[sess.id] = sess
                obs.gauge("serve.sessions.active", self.n_sessions)
            if cur is not None:
                # lost an id race with a concurrent open/attach: fold
                # into the winner (the loser's durable mark is cleared
                # first so closing it cannot reap the winner's segment)
                sess.durable = None
                sess.close()
                with self._lock:
                    self._admitted -= 1
                    self._orphans.pop(cur.id, None)
                return self._attach_payload(cur)
        except BaseException:
            with self._lock:
                self._admitted -= 1
            raise
        return self._attach_payload(sess)

    def _attach_payload(self, sess: Session) -> dict:
        grp = sess.group
        return {"session": sess.id, "slots": grp.n_slots,
                "batch": grp.batch, "store": sess.store is not None,
                "version": sess.version, "incarn": sess.incarn,
                "durable": self.ckpt is not None}

    def _op_attach(self, req: dict) -> dict:
        """Re-attach a resuming client to its durable session id
        (after a reconnect or a server restart): clears the orphan
        clock, transfers connection ownership (via _on_response), and
        returns the open-shaped payload including the session's
        current version and incarnation token."""
        self._sweep_orphans()
        sess = self._session(req)
        with self._lock:
            self._orphans.pop(sess.id, None)
        obs.count("serve.attaches")
        return self._attach_payload(sess)

    def _op_ask(self, req: dict) -> dict:
        sess = self._session(req)
        try:
            n = int(req.get("n", 1))
        except (TypeError, ValueError) as e:
            raise RequestError(f"n must be an integer: {e}")
        t0 = time.perf_counter()
        reissued = False
        try:
            if req.get("reissue"):
                # the resume path: an ask whose reply was lost already
                # ticketed rows out — re-offer the outstanding tickets
                # first so the epoch can settle (new rows only once
                # nothing is outstanding)
                offers = sess.outstanding()
                reissued = bool(offers)
                if not offers:
                    offers = sess.ask(n)
            else:
                offers = sess.ask(n)
        except StaleTicketError as e:
            # a concurrent close between the registry fetch and the
            # ask is a routine client-side race, not a server fault
            raise RequestError(str(e))
        obs.observe("serve.ask_ms", (time.perf_counter() - t0) * 1e3)
        if reissued:
            obs.count("serve.reissues")
        version, served = sess.version, sess.store_served
        incarn = sess.incarn
        out = WireReply(
            ok=True,
            trials=[{"ticket": o.ticket, "config": o.config,
                     "epoch": o.epoch} for o in offers],
            version=version, store_served=served,
            incarn=incarn, reissued=reissued)
        if all(o.canon is not None for o in offers):
            # preserialized reply (ISSUE 20): each offer's canonical
            # config JSON was computed once, at the epoch's dedup
            # scan — a k-wide ask splices k cached fragments instead
            # of re-encoding k config dicts, and a batch frame
            # splices this whole text in turn.  canon_config is
            # value-identical to the raw dict for wire-decoded
            # configs (sorted keys only), so text == dict holds.
            rows = ",".join(
                '{"ticket":%d,"config":%s,"epoch":%d}'
                % (o.ticket, o.canon, o.epoch) for o in offers)
            out.wire_text = (
                '{"ok":true,"trials":[%s],"version":%d,'
                '"store_served":%d,"incarn":%s,"reissued":%s}'
                % (rows, version, served, _enc(incarn),
                   "true" if reissued else "false"))
        return out

    def _op_tell(self, req: dict) -> dict:
        """Single tell (`ticket` + `qor`) or a batch in one round trip
        (`results`: list of {ticket, qor[, dur]} objects) — a tenant
        measuring trials in parallel reports them all at once.  The
        `results` form is the legacy spelling of `tell_many` and
        routes through the same vectorized one-lock-hold path."""
        if "results" in req:
            return self._op_tell_many(req)
        if "ticket" not in req:
            raise RequestError("tell needs 'ticket' or 'results'")
        sess = self._session(req)
        t0 = time.perf_counter()
        # a SINGLE tell keeps the hard ok=False contract: a stale or
        # malformed tell is the whole op's error
        try:
            one = sess.tell(int(req["ticket"]), req.get("qor"),
                            float(req.get("dur", 0.0)),
                            epoch=req.get("epoch"),
                            incarn=req.get("incarn"))
        except StaleTicketError as e:
            raise RequestError(str(e))
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            raise RequestError(f"bad tell payload: {e}")
        dup = bool(one.get("duplicate"))
        out = {"told": 0 if dup else 1,
               "new_best": False if dup else one["new_best"],
               "committed": one["committed"],
               "duplicates": 1 if dup else 0,
               "version": one["version"]}
        obs.observe("serve.tell_ms", (time.perf_counter() - t0) * 1e3)
        return out

    def _op_tell_many(self, req: dict) -> dict:
        """The vectorized batch tell (ISSUE 20): every row applies in
        ONE group-lock hold and the whole batch is acked behind ONE
        checkpoint drain.  Element-wise error walls — one bad/stale
        ticket must not discard the progress of the others (they are
        already told server-side; reporting ok=False would strand the
        epoch): per-row failures come back in `errors`."""
        sess = self._session(req)
        batch = req.get("results")
        if not isinstance(batch, list):
            raise RequestError("'results' must be a list")
        t0 = time.perf_counter()
        out = sess.tell_many(batch, incarn=req.get("incarn"))
        obs.observe("serve.tell_ms", (time.perf_counter() - t0) * 1e3)
        return out

    def _op_best(self, req: dict) -> dict:
        return self._session(req).best()

    def _op_close(self, req: dict) -> dict:
        sess = self._session(req)
        sess.close()
        with self._lock:
            if self._sessions.pop(sess.id, None) is not None:
                self._admitted -= 1
            self._orphans.pop(sess.id, None)
            self._owners.pop(sess.id, None)
            obs.gauge("serve.sessions.active", self.n_sessions)
        return {"closed": sess.id}

    def _op_metrics(self, req: dict) -> dict:
        """The obs-plane scrape (PR 7 left this seam open: metrics
        snapshot() was written as the future session-server payload).
        ``"format": "prometheus"`` returns the text exposition instead
        (docs/SERVING.md), so a textfile collector / sidecar exporter
        can relay the registry without learning the JSON schema."""
        fmt = str(req.get("format", "json")).lower()
        out: Dict[str, Any] = {
            "sessions": self.n_sessions,
            "uptime_s": round(time.time() - self.started_unix, 3)}
        if fmt == "prometheus":
            out["metrics_text"] = obs.prometheus_text()
        elif fmt == "json":
            out["metrics"] = obs.metrics_snapshot()
        else:
            raise RequestError(
                f"metrics format must be json|prometheus: {fmt!r}")
        return out

    # health-op defaults: a serve tenant's epochs are narrow (batch
    # rows, not driver tickets), so the stall bar sits far below the
    # driver-side QualityConfig default; request fields override
    HEALTH_STALL_TELLS = 64
    HEALTH_FAIL_RATE_HI = 0.5
    HEALTH_MAX_SESSIONS = 64
    HEALTH_LIMIT_CAP = 1024

    def _op_health(self, req: dict) -> dict:
        """Per-session search-quality verdicts (ISSUE 12): with a
        ``session`` field, that tenant's health; without, a bounded
        roll-up over every live session — what a sharded front tier
        (ROADMAP item 1) polls to decide placement/eviction.  Optional
        ``stall_tells`` / ``fail_rate_hi`` override the thresholds for
        this request only; ``limit`` bounds the roll-up payload
        (default 64, capped at ``HEALTH_LIMIT_CAP`` so one request
        can never serialize an unbounded session table —
        docs/SERVING.md)."""
        try:
            stall = int(req.get("stall_tells", self.HEALTH_STALL_TELLS))
            frh = float(req.get("fail_rate_hi",
                                self.HEALTH_FAIL_RATE_HI))
            limit = int(req.get("limit", self.HEALTH_MAX_SESSIONS))
        except (TypeError, ValueError) as e:
            raise RequestError(
                f"stall_tells/fail_rate_hi/limit must be numbers: {e}")
        if not 1 <= limit <= self.HEALTH_LIMIT_CAP:
            raise RequestError(
                f"limit must be in [1, {self.HEALTH_LIMIT_CAP}]: "
                f"{limit}")
        if req.get("session") is not None:
            return {"health": self._session(req).health(
                stall_tells=stall, fail_rate_hi=frh)}
        with self._lock:
            sessions = list(self._sessions.values())
        rows = [s.health(stall_tells=stall, fail_rate_hi=frh)
                for s in sessions]
        by_status: Dict[str, int] = {}
        for r in rows:
            by_status[r["status"]] = by_status.get(r["status"], 0) + 1
        # bounded payload: worst-first (failing, stalled, cold, ok) so
        # a truncated roll-up still surfaces every unhealthy tenant
        # ahead of the healthy tail
        rank = {"failing": 0, "stalled": 1, "cold": 2, "ok": 3}
        rows.sort(key=lambda r: (rank.get(r["status"], 4),
                                 r["session"]))
        return {"sessions": len(rows), "by_status": by_status,
                "truncated": len(rows) > limit,
                "health": rows[:limit]}

    def _op_stats(self, req: dict) -> dict:
        with self._lock:
            groups = [{"space": g.key[0][0][:60] if g.key[0] else "",
                       "slots": g.n_slots, "active": g.n_active,
                       "batch": g.batch}
                      for gs in self._groups.values() for g in gs]
            # keyed program@scope-prefix: two stores sharing a program
            # token over DIFFERENT spaces must not overwrite each
            # other in the payload (scope hashes space sig + program)
            stores = {f"{k[1] or '<anon>'}@{s.scope[:10]}": s.stats()
                      for k, s in self._stores.items()}
        out = {"sessions": self.n_sessions, "groups": groups,
               "stores": stores, "store_dir": self.store_dir}
        if req.get("sessions"):
            # the front-tier router's attach probe (serve/router.py):
            # an id the router no longer remembers is located by
            # asking each shard which durable sessions it owns
            with self._lock:
                out["session_ids"] = sorted(self._sessions)
        if self.ckpt is not None:
            self._sweep_orphans()
            with self._lock:
                orphans = len(self._orphans)
            out["durable"] = {**self.ckpt.stats(),
                              "recovered": self.recovered,
                              "recovery_s": self.recovery_s,
                              "orphans": orphans,
                              "orphan_ttl": self.orphan_ttl}
        return out

    _OPS = {"ping": _op_ping, "open": _op_open, "attach": _op_attach,
            "ask": _op_ask, "tell": _op_tell,
            "tell_many": _op_tell_many, "best": _op_best,
            "close": _op_close, "metrics": _op_metrics,
            "stats": _op_stats, "health": _op_health}

    # -- wire hooks (serve/wire.py owns dispatch + the TCP loops) ------
    def _listen_banner(self) -> str:
        return (f" (slots={self.slots}, max-sessions="
                f"{self.max_sessions}, store={self.store_dir or 'off'})")

    def _conn_opened(self, conn, addr) -> set:
        # session lifetime is CONNECTION-scoped: ids opened here are
        # reaped when the connection dies, so a crashed tenant cannot
        # hold its group slot and admission unit forever (a long-lived
        # server would otherwise leak to "server full" under client
        # churn).  Tracked at the transport layer — handle() stays
        # transport-free and in-process sessions are unaffected.
        return set()

    def _on_response(self, owned: set, req: dict, resp: dict) -> None:
        if resp.get("ok") and isinstance(req, dict):
            if req.get("op") in ("open", "attach"):
                sid = resp["session"]
                owned.add(sid)
                with self._lock:
                    # ownership MOVES to this connection, and a live
                    # owner means no orphan clock is running
                    self._owners[sid] = id(owned)
                    self._orphans.pop(sid, None)
            elif req.get("op") == "close":
                owned.discard(resp.get("closed"))

    def _conn_closed(self, owned: set) -> None:
        for sid in owned:   # best-effort: never raises
            if self.ckpt is not None:
                # durable sessions get an orphan grace window instead
                # of the instant reap: a resuming client re-attaches
                # (clearing the clock); a dead one is swept lazily
                # after orphan_ttl.  Only the CURRENT owner may start
                # the clock — a lingering old connection dying after
                # its client re-attached elsewhere owns nothing here
                with self._lock:
                    if (sid in self._sessions
                            and self._owners.get(sid) == id(owned)):
                        self._orphans[sid] = time.time()
                        self._owners.pop(sid, None)
            else:
                self.handle({"op": "close", "session": sid})

    def stop(self) -> None:
        super().stop()      # listener + live connections
        # snapshot under _lock: handler threads may still be mutating
        # the registry (an open inside _store_for) while shutdown
        # walks it
        with self._lock:
            stores = list(self._stores.values())
        for st in stores:
            st.close()
