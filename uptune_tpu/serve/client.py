"""Python client for the session server: ``ut.connect()``.

    import uptune_tpu as ut
    from uptune_tpu.workloads import rosenbrock_space

    client = ut.connect("127.0.0.1:8765")
    s = client.open_session(rosenbrock_space(2, -3, 3), seed=7,
                            program="rosen-demo")
    for _ in range(200):
        for t in s.ask(4):
            s.tell(t.ticket, measure(t.config))
    print(s.best())
    s.close(); client.close()

One ``SessionClient`` is one TCP connection; it may multiplex ANY
number of sessions (requests are synchronous per connection and
serialized by an internal lock — open several clients for parallel
request streams).  Spaces are sent as JSON param records; a library
``Space`` is serialized via ``exec.space_io.records_from_space``.
"""
from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Union

from .. import obs


class ServeError(RuntimeError):
    """The server answered ok=False."""


class Trial(NamedTuple):
    """One proposed trial: measure `config`, tell `ticket`."""
    ticket: int
    config: Dict[str, Any]


def _parse_addr(addr: Union[str, tuple, None]) -> tuple:
    from ..api.session import settings
    if addr is None:
        return (str(settings["serve-host"]), int(settings["serve-port"]))
    if isinstance(addr, (tuple, list)):
        return (str(addr[0]), int(addr[1]))
    host, _, port = str(addr).rpartition(":")
    if not host:
        raise ValueError(f"address must be 'host:port', got {addr!r}")
    return (host, int(port))


def connect(addr: Union[str, tuple, None] = None,
            timeout: float = 60.0) -> "SessionClient":
    """Open a client connection (`addr` = "host:port", a (host, port)
    pair, or None for the configured serve-host/serve-port)."""
    return SessionClient(*_parse_addr(addr), timeout=timeout)


class SessionClient:
    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.host, self.port = host, int(port)
        self._sock = socket.create_connection((host, self.port),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._f = self._sock.makefile("rwb")
        self._lock = threading.Lock()
        self._broken = False

    # -- wire ----------------------------------------------------------
    def request(self, op: str, **fields: Any) -> Dict[str, Any]:
        """One synchronous request/response; raises ServeError on
        ok=False.

        Trace-context propagation (docs/OBSERVABILITY.md): when THIS
        process is tracing, the request carries a ``ctx`` span id and
        the round trip is recorded as a ``client.request`` span tagged
        with it; the server's matching ``serve.handle`` span carries
        the same id as ``parent``, so `ut-trace merge` joins the two
        shards and decomposes client-observed latency into wire vs
        server time.  Untraced clients send no extra field."""
        payload = {"op": op, **{k: v for k, v in fields.items()
                                if v is not None}}
        sid = None
        t0 = 0.0
        if obs.enabled():
            sid = obs.new_span_id()
            payload["ctx"] = {"span": sid}
            t0 = time.perf_counter()
        with self._lock:
            # a request that died mid-exchange (socket timeout,
            # KeyboardInterrupt out of readline) leaves its response
            # in flight; the NEXT request would silently consume it
            # as its own.  The connection is desynced — refuse it.
            if self._broken:
                raise ServeError(
                    "connection desynced by an interrupted request; "
                    "reconnect")
            try:
                self._f.write(json.dumps(payload,
                                         separators=(",", ":"))
                              .encode() + b"\n")
                self._f.flush()
                line = self._f.readline()
            except BaseException:
                self._broken = True
                raise
        if sid is not None:
            obs.complete_span("client.request", t0=t0,
                              dur=time.perf_counter() - t0,
                              op=op, ctx=sid,
                              server=f"{self.host}:{self.port}")
        if not line:
            raise ServeError(f"server {self.host}:{self.port} closed "
                             f"the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise ServeError(resp.get("error", "unknown server error"))
        return resp

    # -- surface -------------------------------------------------------
    def ping(self) -> Dict[str, Any]:
        return self.request("ping")

    def metrics(self, format: Optional[str] = None) -> Dict[str, Any]:
        """The server's obs metrics scrape (counters / gauges /
        histogram summaries — docs/OBSERVABILITY.md names).
        ``format="prometheus"`` returns the text exposition in
        ``metrics_text`` instead of the JSON snapshot."""
        return self.request("metrics", format=format)

    def stats(self) -> Dict[str, Any]:
        return self.request("stats")

    def health(self, session: Optional[str] = None,
               **thresholds: Any) -> Dict[str, Any]:
        """Per-session search-quality verdicts (docs/SERVING.md): a
        session id narrows to one tenant; without it the server
        returns a bounded worst-first roll-up.  `stall_tells=` /
        `fail_rate_hi=` override the server thresholds per call."""
        return self.request("health", session=session, **thresholds)

    def open_session(self, space: Any, *, seed: int = 0,
                     program: str = "",
                     sense: str = "min",
                     arms: Optional[Sequence[str]] = None,
                     history_capacity: int = 1 << 10,
                     store: bool = True) -> "SessionHandle":
        """Open one tuning session.  `space` is a library Space or a
        list of JSON param records; `program` is the tenant-declared
        token naming WHAT is being measured — sessions naming the same
        program over the same space share the server's cross-tenant
        result memo."""
        if not isinstance(space, (list, tuple)):
            from ..exec.space_io import records_from_space
            space = records_from_space(space)
        resp = self.request(
            "open", space=list(space), seed=int(seed),
            program=str(program), sense=sense,
            arms=list(arms) if arms else None,
            history_capacity=int(history_capacity),
            store="on" if store else "off")
        return SessionHandle(self, resp["session"], resp)

    def close(self) -> None:
        try:
            self._f.close()
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "SessionClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class SessionHandle:
    """One session on one client: ask / tell / best / close."""

    def __init__(self, client: SessionClient, session_id: str,
                 info: Optional[dict] = None):
        self.client = client
        self.id = session_id
        self.info = dict(info or {})
        self.version = 0
        self.store_served = 0

    def ask(self, n: int = 1) -> List[Trial]:
        resp = self.client.request("ask", session=self.id, n=int(n))
        self.version = resp.get("version", self.version)
        self.store_served = resp.get("store_served", self.store_served)
        return [Trial(t["ticket"], t["config"])
                for t in resp["trials"]]

    def tell(self, ticket: int, qor: Optional[float],
             dur: float = 0.0) -> Dict[str, Any]:
        resp = self.client.request("tell", session=self.id,
                                   ticket=int(ticket), qor=qor,
                                   dur=dur or None)
        self.version = resp.get("version", self.version)
        return resp

    def tell_many(self, results) -> Dict[str, Any]:
        """Report many (ticket, qor) pairs in ONE round trip."""
        resp = self.client.request(
            "tell", session=self.id,
            results=[{"ticket": int(t), "qor": q} for t, q in results])
        self.version = resp.get("version", self.version)
        return resp

    def best(self) -> Dict[str, Any]:
        return self.client.request("best", session=self.id)

    def health(self, **thresholds: Any) -> Dict[str, Any]:
        """This session's quality verdict ({"op": "health"})."""
        return self.client.request("health", session=self.id,
                                   **thresholds)["health"]

    def close(self) -> None:
        try:
            self.client.request("close", session=self.id)
        except (ServeError, OSError):
            # already closed server-side, or the connection is gone —
            # the server reaps dead connections' sessions anyway
            pass

    def __enter__(self) -> "SessionHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
