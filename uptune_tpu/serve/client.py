"""Python client for the session server: ``ut.connect()``.

    import uptune_tpu as ut
    from uptune_tpu.workloads import rosenbrock_space

    client = ut.connect("127.0.0.1:8765")
    s = client.open_session(rosenbrock_space(2, -3, 3), seed=7,
                            program="rosen-demo")
    for _ in range(200):
        for t in s.ask(4):
            s.tell(t.ticket, measure(t.config))
    print(s.best())
    s.close(); client.close()

One ``SessionClient`` is one TCP connection; it may multiplex ANY
number of sessions (requests are synchronous per connection and
serialized by an internal lock — open several clients for parallel
request streams).  Spaces are sent as JSON param records; a library
``Space`` is serialized via ``exec.space_io.records_from_space``.

Auto-resume (ISSUE 15, docs/SERVING.md "Durability & failover"):
``connect(addr, auto_resume=True)`` makes the connection crash-safe
against both transient network failures and full server restarts.
Every op gets a bounded socket timeout; on a connection failure the
client reconnects with exponential backoff plus jitter, re-attaches
each of its sessions by durable id, and replays only the idempotent
frontier:

* ``open`` carries a client-minted session id, so a retried open
  whose ack was lost re-attaches instead of leaking a session;
* a retried ``ask`` carries ``reissue``, so tickets the lost reply
  already handed out are re-offered rather than stranded;
* ``tell`` carries the ticket's epoch id and the session's
  incarnation token, so a duplicate replay after an
  acked-but-unobserved reply is detected and squashed server-side.

The one failure auto-resume surfaces instead of hiding: a ticket
from an in-flight epoch a server CRASH destroyed (the bounded-loss
contract) fails with a "restored" ServeError — re-``ask()`` and
retry with the fresh tickets.

Batched wire plane (ISSUE 20): ``SessionClient.batch(payloads)``
sends one multi-op frame (one round trip, ordered reply list,
per-sub-op error ENTRIES); ``ask_many``/``tell_many`` drive many
sessions' hot ops through one frame.  A torn frame replays whole
under auto-resume — every sub-op carries the resume protocol's
idempotency tags (replayed asks gain ``reissue``), so the replay is
idempotent by construction.  Down-level servers are sniffed from the
unknown-op error reply once (one loud log) and the client falls back
to sequential requests / the legacy ``tell``+``results`` spelling.
"""
from __future__ import annotations

import json
import logging
import socket
import threading
import time
import uuid
from typing import (Any, Dict, Iterable, List, NamedTuple, Optional,
                    Sequence, Tuple, Union)

from .. import obs
from ..obs.ship import backoff_jitter
from ..utils.net import reject_self_connect

log = logging.getLogger("uptune_tpu")

# one reusable encoder for every request this process writes (the
# serve/wire reply-side twin): json.dumps re-resolves its options on
# every call, measurable at batched-frame request rates
_ENC = json.JSONEncoder(separators=(",", ":"),
                        check_circular=False).encode


class ServeError(RuntimeError):
    """The server answered ok=False."""


class ConnectionLostError(ServeError):
    """The connection died mid-exchange (closed, timed out, or
    desynced) — the retryable class auto-resume acts on."""


class Trial(NamedTuple):
    """One proposed trial: measure `config`, tell `ticket`.  `epoch`
    is the ticket's session-version tag, echoed on tell so resume
    replays are idempotent."""
    ticket: int
    config: Dict[str, Any]
    epoch: int = 0


def _parse_addr(addr: Union[str, tuple, None]) -> tuple:
    from ..api.session import settings
    if addr is None:
        return (str(settings["serve-host"]), int(settings["serve-port"]))
    if isinstance(addr, (tuple, list)):
        return (str(addr[0]), int(addr[1]))
    host, _, port = str(addr).rpartition(":")
    if not host:
        raise ValueError(f"address must be 'host:port', got {addr!r}")
    return (host, int(port))


def _mark_reissue(payload: Dict[str, Any]) -> None:
    """Stamp a replayed request's ask(s) with ``reissue`` so tickets
    the lost reply already handed out are re-offered, never re-minted
    — for a batch frame, every ask sub-op is stamped (the torn-frame
    replay is idempotent by construction: tells carry epoch+incarn
    tags, asks reissue, and everything else is naturally replayable)."""
    if payload.get("op") == "ask":
        payload["reissue"] = True
    elif payload.get("op") == "batch":
        for sub in payload.get("ops") or ():
            if isinstance(sub, dict) and sub.get("op") == "ask":
                sub["reissue"] = True


def connect(addr: Union[str, tuple, None] = None,
            timeout: float = 60.0, **kw: Any) -> "SessionClient":
    """Open a client connection (`addr` = "host:port", a (host, port)
    pair, or None for the configured serve-host/serve-port).  Keyword
    arguments (`auto_resume`, `op_timeout`, `max_retries`, ...) pass
    through to SessionClient."""
    host, port = _parse_addr(addr)
    return SessionClient(host, port, timeout=timeout, **kw)


class SessionClient:
    # bound on {"redirect": ...} hops one request may follow — a
    # misconfigured router pair bouncing a key between themselves
    # must surface as an error, not an infinite reconnect loop
    MAX_REDIRECTS = 4

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 *, op_timeout: Optional[float] = None,
                 auto_resume: bool = False, max_retries: int = 8,
                 backoff_base: float = 0.25, backoff_max: float = 5.0):
        self.host, self.port = host, int(port)
        self.connect_timeout = float(timeout)
        # bounded per-op timeout: defaults to the connect timeout so
        # no request can hang forever (the pre-ISSUE-15 behavior kept
        # the connect timeout on the socket; this makes it explicit
        # and independently tunable)
        self.op_timeout = float(op_timeout if op_timeout is not None
                                else timeout)
        self.auto_resume = bool(auto_resume)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._lock = threading.Lock()
        # serializes the reconnect+reattach sequence across threads
        # sharing this client (a separate lock: _reattach exchanges
        # under _lock, so holding _lock across it would deadlock)
        self._resume_lock = threading.Lock()
        self._broken = False
        self._sock: Optional[socket.socket] = None
        self._f = None
        # durable session ids this client opened (or attached), in
        # open order — re-attached after every reconnect so the new
        # connection owns them server-side
        self._resume_ids: List[str] = []
        self.reconnects = 0
        # down-level server sniffing (ISSUE 20): None = unknown,
        # True = the server speaks it, False = fell back (one loud
        # log at the flip, then quiet sequential/legacy compat)
        self._batch_ok: Optional[bool] = None
        self._tell_many_ok: Optional[bool] = None
        # redirect hops followed (the sharded front tier, ISSUE 17):
        # a router answers open/attach with {"redirect": "host:port"}
        # and the client re-homes the whole connection onto the
        # owning shard — steady-state ask/tell never crosses the
        # router again
        self.redirects = 0
        self._connect()

    # -- wire ----------------------------------------------------------
    def _connect(self) -> None:
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.connect_timeout)
        reject_self_connect(s, f"{self.host}:{self.port}")
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(self.op_timeout)
        self._sock = s
        self._f = s.makefile("rwb")
        self._broken = False

    def _drop_conn(self) -> None:
        try:
            if self._f is not None:
                self._f.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._f = None
        self._sock = None
        self._broken = True

    def _exchange(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """One synchronous request/response on the current connection;
        raises ConnectionLostError when the exchange cannot complete
        (and marks the connection broken: a died-mid-exchange reply
        may still be in flight, and the NEXT request would silently
        consume it as its own — the stream is desynced).

        Trace-context propagation (docs/OBSERVABILITY.md): when THIS
        process is tracing, the request carries a ``ctx`` span id and
        the round trip is recorded as a ``client.request`` span tagged
        with it; the server's matching ``serve.handle`` span carries
        the same id as ``parent``, so `ut-trace merge` joins the two
        shards and decomposes client-observed latency into wire vs
        server time.  Untraced clients send no extra field."""
        sid = None
        t0 = 0.0
        if obs.enabled():
            sid = obs.new_span_id()
            payload = {**payload, "ctx": {"span": sid}}
            t0 = time.perf_counter()
        with self._lock:
            if self._broken or self._f is None:
                raise ConnectionLostError(
                    "connection desynced by an interrupted request; "
                    "reconnect")
            try:
                self._f.write(_ENC(payload).encode() + b"\n")
                self._f.flush()
                line = self._f.readline()
            except BaseException as e:
                self._broken = True
                if isinstance(e, (OSError, ValueError)):
                    raise ConnectionLostError(
                        f"request {payload.get('op')!r} died "
                        f"mid-exchange: {e}") from e
                raise
        if sid is not None:
            obs.complete_span("client.request", t0=t0,
                              dur=time.perf_counter() - t0,
                              op=payload.get("op"), ctx=sid,
                              server=f"{self.host}:{self.port}")
        if not line:
            self._broken = True
            raise ConnectionLostError(
                f"server {self.host}:{self.port} closed the "
                f"connection")
        try:
            resp = json.loads(line)
        except ValueError as e:
            # a server dying mid-reply flushes a PARTIAL line; the
            # EOF readline returns it non-empty, so this is the same
            # connection-loss case as the empty read — it must reach
            # the resume machinery, not the caller
            self._broken = True
            raise ConnectionLostError(
                f"truncated reply from {self.host}:{self.port}: {e}"
            ) from e
        if not resp.get("ok"):
            raise ServeError(resp.get("error", "unknown server error"))
        return resp

    def _reattach(self) -> None:
        """Re-own this client's sessions on a fresh connection.  A
        session the server no longer knows (closed, orphan-swept, or
        unrecoverable) is PRUNED and the rest still attach — one dead
        session must not fail unrelated handles' ops on every
        reconnect, or leave a live sibling un-attached with its
        server-side orphan clock running.  The dead session surfaces
        naturally: its own handle's next op gets 'unknown session'.
        Connection-level failures still raise (the retry loop's
        business)."""
        for sid in list(self._resume_ids):
            try:
                self._exchange({"op": "attach", "session": sid})
            except ConnectionLostError:
                raise
            except ServeError:
                self._forget(sid)

    def request(self, op: str, **fields: Any) -> Dict[str, Any]:
        """One synchronous request/response; raises ServeError on
        ok=False.  With ``auto_resume``, connection failures
        reconnect with exponential backoff+jitter, re-attach every
        session this client owns, and replay the request with its
        idempotency tags (a replayed ``ask`` adds ``reissue`` so
        already-issued tickets are re-offered, never re-minted).

        A reply carrying ``redirect: "host:port"`` (the sharded front
        tier's open/attach answer) re-homes this client: the
        connection moves to the owning shard and the request is
        re-sent there, bounded by ``MAX_REDIRECTS`` hops.  Because
        ``self.host``/``self.port`` move too, every later reconnect —
        including auto-resume after a shard death — targets the shard
        directly, never the router."""
        payload = {"op": op, **{k: v for k, v in fields.items()
                                if v is not None}}
        attempt = 0
        hops = 0
        backoff = self.backoff_base
        while True:
            try:
                if self._broken or self._f is None:
                    if not self.auto_resume and hops == 0:
                        raise ConnectionLostError(
                            "connection desynced by an interrupted "
                            "request; reconnect")
                    # one thread reconnects; peers that also observed
                    # the break queue here and RE-CHECK — without the
                    # serialization, each thread's _drop_conn would
                    # keep closing the connection a peer just rebuilt
                    # and mutual interference could burn max_retries
                    # against a perfectly healthy server
                    with self._resume_lock:
                        if self._broken or self._f is None:
                            self._drop_conn()
                            self._connect()
                            self.reconnects += 1
                            self._reattach()
                    _mark_reissue(payload)
                resp = self._exchange(payload)
                target = resp.get("redirect")
                if isinstance(target, str) and target:
                    if hops >= self.MAX_REDIRECTS:
                        raise ServeError(
                            f"redirect limit ({self.MAX_REDIRECTS}) "
                            f"exceeded following {target!r}")
                    hops += 1
                    self.redirects += 1
                    host, _, port = target.rpartition(":")
                    self.host, self.port = (host or self.host,
                                            int(port))
                    # drop the old connection; the reconnect branch
                    # above re-dials the NEW address (and re-attaches
                    # any sessions this client already owns there)
                    with self._lock:
                        self._drop_conn()
                    continue
                return resp
            except (ConnectionLostError, OSError) as e:
                attempt += 1
                self._broken = True
                if not self.auto_resume or attempt > self.max_retries:
                    raise
                # jittered exponential backoff (the shipper's rule:
                # a fleet of resuming clients must not stampede the
                # restarted server in lockstep)
                time.sleep(backoff_jitter(backoff))
                backoff = min(self.backoff_max, backoff * 2)

    # -- batched wire plane (ISSUE 20) ---------------------------------
    def _note_downlevel(self, what: str) -> None:
        """One loud log the first time a down-level server is sniffed;
        the compat fallback stays quiet after that."""
        log.warning(
            "[ut-client] server %s:%d does not speak %r (pre-batched "
            "wire plane); falling back to the legacy spelling for "
            "this connection", self.host, self.port, what)

    def batch(self, payloads: Sequence[Dict[str, Any]]
              ) -> List[Dict[str, Any]]:
        """Send many requests as ONE multi-op frame: one round trip,
        one ordered reply list.  Each payload is a full request dict
        (``{"op": ..., ...}``); each reply is that sub-op's full
        response — per-sub-op failures come back as ``ok=False``
        ENTRIES, never raised, so one bad sub-op cannot discard its
        siblings' results.  Frame-level failures raise ServeError.

        Under auto-resume a torn frame replays whole (see
        _mark_reissue — idempotent by construction).  A server
        without the batch op is sniffed from its unknown-op reply
        (once, loudly) and the frame degrades to sequential requests.

        Note: ``open``/``attach`` sub-ops are not registered for
        auto-reattach — use ``open_session``/``attach_session`` for
        sessions that must survive reconnects."""
        if self._batch_ok is False:
            return self._batch_fallback(payloads)
        ops = [dict(p) for p in payloads]
        try:
            resp = self.request("batch", ops=ops)
        except ServeError as e:
            if (self._batch_ok is None
                    and "unknown op" in str(e)):
                self._batch_ok = False
                self._note_downlevel("batch")
                return self._batch_fallback(payloads)
            raise
        self._batch_ok = True
        replies = resp.get("replies")
        if not isinstance(replies, list) or len(replies) != len(ops):
            raise ServeError(
                f"batch reply carries {len(replies or ())} replies "
                f"for {len(ops)} ops")
        return replies

    def _batch_fallback(self, payloads: Sequence[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
        """The down-level degradation: one request per payload,
        errors folded into ok=False entries (the frame's element-wise
        contract, minus the single round trip)."""
        out: List[Dict[str, Any]] = []
        for p in payloads:
            op = p.get("op")
            fields = {k: v for k, v in p.items() if k != "op"}
            if op == "tell_many" and self._tell_many_ok is False:
                # the server is already known down-level: go straight
                # to the legacy tell+results spelling (same fields)
                op = "tell"
            try:
                out.append(self.request(op, **fields))
                continue
            except ServeError as e:
                if (op == "tell_many" and self._tell_many_ok is not
                        False and "unknown op" in str(e)):
                    self._tell_many_ok = False
                    self._note_downlevel("tell_many")
                    try:
                        out.append(self.request("tell", **fields))
                        continue
                    except ServeError as e2:
                        e = e2
                out.append({"ok": False, "error": str(e)})
        return out

    def ask_many(self, handles: Sequence["SessionHandle"],
                 n: int = 1) -> List[List["Trial"]]:
        """One batched ask across many sessions: a single width-k
        frame replaces k round trips (the per-shard ceiling lever
        BENCH_SERVE's batched_wire phase prices).  Returns each
        handle's trials in order; a failed sub-ask raises."""
        replies = self.batch([{"op": "ask", "session": h.id,
                               "n": int(n)} for h in handles])
        out = []
        for h, r in zip(handles, replies):
            if not r.get("ok"):
                raise ServeError(r.get("error",
                                       "unknown server error"))
            out.append(h._absorb_ask(r))
        return out

    def tell_many(self, batches: Sequence[
            Tuple["SessionHandle", Iterable[Tuple[int, Any]]]]
                  ) -> List[Dict[str, Any]]:
        """One batched tell across many sessions: each (handle,
        results) pair becomes one vectorized ``tell_many`` sub-op —
        one frame, one reply per session, every tell acked behind its
        session's single durable drain.  A failed sub-op raises;
        per-TICKET failures stay element-wise inside each reply's
        ``errors`` list."""
        payloads, hs, tks = [], [], []
        for h, results in batches:
            rows = h._tell_rows(results)
            payloads.append({"op": "tell_many", "session": h.id,
                             "results": rows, "incarn": h.incarn})
            hs.append(h)
            tks.append([r["ticket"] for r in rows])
        replies = self.batch(payloads)
        out = []
        for h, tickets, r in zip(hs, tks, replies):
            if not r.get("ok"):
                raise ServeError(r.get("error",
                                       "unknown server error"))
            h._after_tell(r, tickets)
            out.append(r)
        return out

    def resolve(self, spaces: Sequence[Any]) -> List[Dict[str, Any]]:
        """Against a router: map many spaces to their owning shards
        in ONE round trip (each entry a Space or a list of param
        records) — open each session directly against its shard
        afterwards instead of paying a redirect hop per open.
        Returns one ``{"shard", "addr", "key"}`` row per entry
        (``{"error"}`` rows element-wise)."""
        from ..exec.space_io import records_from_space
        recs = [list(s) if isinstance(s, (list, tuple))
                else records_from_space(s) for s in spaces]
        return self.request("resolve", spaces=recs)["resolved"]

    # -- surface -------------------------------------------------------
    def ping(self) -> Dict[str, Any]:
        return self.request("ping")

    def metrics(self, format: Optional[str] = None) -> Dict[str, Any]:
        """The server's obs metrics scrape (counters / gauges /
        histogram summaries — docs/OBSERVABILITY.md names).
        ``format="prometheus"`` returns the text exposition in
        ``metrics_text`` instead of the JSON snapshot."""
        return self.request("metrics", format=format)

    def stats(self) -> Dict[str, Any]:
        return self.request("stats")

    def health(self, session: Optional[str] = None,
               **thresholds: Any) -> Dict[str, Any]:
        """Per-session search-quality verdicts (docs/SERVING.md): a
        session id narrows to one tenant; without it the server
        returns a bounded worst-first roll-up.  `stall_tells=` /
        `fail_rate_hi=` override the server thresholds per call."""
        return self.request("health", session=session, **thresholds)

    def open_session(self, space: Any, *, seed: int = 0,
                     program: str = "",
                     sense: str = "min",
                     arms: Optional[Sequence[str]] = None,
                     history_capacity: int = 1 << 10,
                     store: bool = True) -> "SessionHandle":
        """Open one tuning session.  `space` is a library Space or a
        list of JSON param records; `program` is the tenant-declared
        token naming WHAT is being measured — sessions naming the same
        program over the same space share the server's cross-tenant
        result memo.  Under ``auto_resume`` the session id is minted
        client-side, so a retried open re-attaches instead of leaking
        a second session."""
        if not isinstance(space, (list, tuple)):
            from ..exec.space_io import records_from_space
            space = records_from_space(space)
        sid = uuid.uuid4().hex[:16] if self.auto_resume else None
        resp = self.request(
            "open", space=list(space), seed=int(seed),
            program=str(program), sense=sense,
            arms=list(arms) if arms else None,
            history_capacity=int(history_capacity),
            store="on" if store else "off", session=sid)
        if self.auto_resume:
            self._resume_ids.append(resp["session"])
        return SessionHandle(self, resp["session"], resp)

    def attach_session(self, session_id: str) -> "SessionHandle":
        """Re-attach to a durable session by id (e.g. after this
        CLIENT process restarted — the server-restart case is handled
        transparently by auto_resume)."""
        resp = self.request("attach", session=str(session_id))
        if self.auto_resume and resp["session"] not in self._resume_ids:
            self._resume_ids.append(resp["session"])
        return SessionHandle(self, resp["session"], resp)

    def _forget(self, session_id: str) -> None:
        try:
            self._resume_ids.remove(session_id)
        except ValueError:
            pass

    def close(self) -> None:
        self._resume_ids.clear()
        self._drop_conn()

    def __enter__(self) -> "SessionClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class SessionHandle:
    """One session on one client: ask / tell / best / close.  Tracks
    the per-ticket epoch tags and the session's incarnation token so
    every tell carries the resume protocol's idempotency fields."""

    def __init__(self, client: SessionClient, session_id: str,
                 info: Optional[dict] = None):
        self.client = client
        self.id = session_id
        self.info = dict(info or {})
        self.version = int(self.info.get("version", 0))
        self.incarn = self.info.get("incarn")
        self.store_served = 0
        self._ticket_epoch: Dict[int, int] = {}

    def _absorb_ask(self, resp: Dict[str, Any]) -> List[Trial]:
        """Fold one ask reply into this handle's resume bookkeeping
        (version, incarnation, per-ticket epoch tags) — shared by the
        single-request path and the batched-frame path."""
        self.version = resp.get("version", self.version)
        self.incarn = resp.get("incarn", self.incarn)
        self.store_served = resp.get("store_served", self.store_served)
        out = [Trial(t["ticket"], t["config"],
                     int(t.get("epoch", self.version)))
               for t in resp["trials"]]
        for t in out:
            self._ticket_epoch[t.ticket] = t.epoch
        return out

    def ask(self, n: int = 1) -> List[Trial]:
        return self._absorb_ask(
            self.client.request("ask", session=self.id, n=int(n)))

    def ask_many(self, n: int) -> List[Trial]:
        """`ask(n)` under its batched-plane name: a single ask is
        already k-wide in one round trip (the server issues the k
        tickets in one group-lock hold).  Cross-SESSION batching is
        where frames earn their keep — see SessionClient.ask_many."""
        return self.ask(n)

    def _after_tell(self, resp: Dict[str, Any], tickets) -> None:
        self.version = resp.get("version", self.version)
        for t in tickets:
            self._ticket_epoch.pop(t, None)

    def _tell_rows(self, results) -> List[Dict[str, Any]]:
        return [{"ticket": int(t), "qor": q,
                 "epoch": self._ticket_epoch.get(int(t))}
                for t, q in results]

    def tell(self, ticket: int, qor: Optional[float],
             dur: float = 0.0) -> Dict[str, Any]:
        resp = self.client.request(
            "tell", session=self.id, ticket=int(ticket), qor=qor,
            dur=dur or None,
            epoch=self._ticket_epoch.get(int(ticket)),
            incarn=self.incarn)
        self._after_tell(resp, [int(ticket)])
        return resp

    def tell_many(self, results) -> Dict[str, Any]:
        """Report many (ticket, qor) pairs in ONE round trip over the
        vectorized ``tell_many`` op: the server applies the whole
        batch in one group-lock hold and acks it behind one durable
        drain (ISSUE 20).  A server predating the op is sniffed from
        its unknown-op reply (once, loudly) and this handle's batches
        ride the legacy ``tell``+``results`` spelling instead."""
        rows = self._tell_rows(results)
        c = self.client
        if c._tell_many_ok is False:
            resp = c.request("tell", session=self.id, results=rows,
                             incarn=self.incarn)
        else:
            try:
                resp = c.request("tell_many", session=self.id,
                                 results=rows, incarn=self.incarn)
                c._tell_many_ok = True
            except ServeError as e:
                if (c._tell_many_ok is None
                        and "unknown op" in str(e)):
                    c._tell_many_ok = False
                    c._note_downlevel("tell_many")
                    resp = c.request("tell", session=self.id,
                                     results=rows, incarn=self.incarn)
                else:
                    raise
        self._after_tell(resp, [r["ticket"] for r in rows])
        return resp

    def best(self) -> Dict[str, Any]:
        return self.client.request("best", session=self.id)

    def health(self, **thresholds: Any) -> Dict[str, Any]:
        """This session's quality verdict ({"op": "health"})."""
        return self.client.request("health", session=self.id,
                                   **thresholds)["health"]

    def close(self) -> None:
        self.client._forget(self.id)
        try:
            self.client.request("close", session=self.id)
        except (ServeError, OSError):
            # already closed server-side, or the connection is gone —
            # the server reaps dead connections' sessions anyway
            pass

    def __enter__(self) -> "SessionHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
