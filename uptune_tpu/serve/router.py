"""The sharded front tier: `ut route` (ISSUE 17, docs/SERVING.md
"Sharded front tier").

One ``SessionServer`` process tops out where Python tops out: a single
interpreter's worth of commit work.  This module scales PAST that
without giving up any of the single-server story — durability, strict
parity, auto-resume — by running K independent `ut serve --durable`
shard processes behind one lightweight **router** process on the same
wire kernel:

* **Routing is consistent hashing by space signature.**  Sessions
  sharing a space signature must land on the SAME shard — that is
  where cross-tenant proposal batching (one ``BatchedEngine`` group)
  and the shared store memo live — so the routing key is the sha1 of
  the open request's canonical space records.  A ``HashRing`` with
  virtual nodes maps key -> shard; adding or removing one shard moves
  only ~1/K of the key space (every other tenant's session placement
  is undisturbed — the property a modulo table lacks).
* **The router redirects; it never proxies.**  ``open``/``attach``
  answer with ``{"redirect": "host:port"}`` and the client reconnects
  straight to the owning shard (serve/client.py follows redirects
  transparently).  Steady-state ask/tell traffic therefore never
  crosses the router — it adds one extra round trip per session
  LIFETIME, not per op, and the front tier can be this single thin
  process.
* **Shards are supervised.**  Each shard is a child `ut serve
  --durable` with its OWN checkpoint dir (recovery isolation) sharing
  ONE ``--store-dir`` (the cross-tenant memo survives resharding).  A
  supervisor thread reaps dead shards and respawns them on the SAME
  port, so the PR 15 client auto-resume protocol — reconnect with
  backoff, re-attach by durable id, replay the idempotent frontier —
  recovers routed sessions with zero acked committed loss and no
  router cooperation at all.  The ``route.spawn``/``route.kill`` fault
  points (obs/faults.py) make shard death deterministic for
  ``bench.py --serve-sharded``.
* **Telemetry aggregates through an embedded hub.**  Every shard
  ships its metrics windows and health rollups to a private
  ``TelemetryHub`` inside the router; the router's ``metrics`` /
  ``sources`` / ``health`` ops re-serve the hub's fleet rollup in the
  session-server scrape shape, with the few population gauges
  (``serve.sessions.active``, batch fill) re-aggregated as sums over
  live shards — so ``ut top --addr <router>`` renders the whole fleet
  as one serving plane, and ``--fleet`` lists the per-shard rows.

The supervisor also converges shard count toward ``target`` (the
``scale`` op moves it at runtime): scale-up spawns and ring-joins new
shards; scale-down removes drained shards from the ring first so no
NEW session routes there while existing tenants finish.
"""
from __future__ import annotations

import bisect
import hashlib
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import obs
from ..obs import faults
from ..obs.hub import TelemetryHub
from .wire import RequestError, WireServer

log = logging.getLogger("uptune_tpu")

__all__ = ["HashRing", "Router", "routing_key", "main"]

# how many sid -> shard placements the router remembers (closed
# sessions never report back, so the map is an LRU-ish bound, not a
# registry; an evicted id still attaches via the shard probe)
SESSION_MAP_CAP = 1 << 16


def routing_key(records: Any) -> str:
    """The consistent-hash key for one open request: sha1 over the
    canonical JSON of the declared space records.  Pure function of
    the space a tenant declares — tenants sharing a space signature
    hash identically and land on one shard, where they share a
    BatchedEngine group and a store scope.  The program token is NOT
    part of the key: programs partition the store, not the engine
    group, and keeping same-space programs co-resident preserves the
    cross-program batching the single server had."""
    blob = json.dumps(records, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()


class HashRing:
    """Consistent hashing with virtual nodes.  Not thread-safe — the
    router mutates it under its own lock."""

    def __init__(self, replicas: int = 64):
        self.replicas = int(replicas)
        self._hashes: List[int] = []        # sorted vnode hashes
        self._owner: Dict[int, str] = {}    # vnode hash -> node name
        self._nodes: set = set()

    @staticmethod
    def _hash(token: str) -> int:
        return int.from_bytes(
            hashlib.sha1(token.encode()).digest()[:8], "big")

    def add(self, name: str) -> None:
        if name in self._nodes:
            return
        self._nodes.add(name)
        for i in range(self.replicas):
            h = self._hash(f"{name}#{i}")
            # vnode collisions across 64-bit sha1 prefixes are
            # ignorable; last-add-wins keeps the map consistent
            if h not in self._owner:
                bisect.insort(self._hashes, h)
            self._owner[h] = name

    def remove(self, name: str) -> None:
        if name not in self._nodes:
            return
        self._nodes.discard(name)
        for i in range(self.replicas):
            h = self._hash(f"{name}#{i}")
            if self._owner.get(h) == name:
                del self._owner[h]
                idx = bisect.bisect_left(self._hashes, h)
                if idx < len(self._hashes) and self._hashes[idx] == h:
                    self._hashes.pop(idx)

    def lookup(self, key: str) -> Optional[str]:
        """The node owning `key` (clockwise successor of its hash), or
        None on an empty ring."""
        if not self._hashes:
            return None
        h = self._hash(key)
        idx = bisect.bisect_right(self._hashes, h)
        if idx == len(self._hashes):
            idx = 0
        return self._owner[self._hashes[idx]]

    @property
    def nodes(self) -> List[str]:
        return sorted(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)


class _Shard:
    """One managed shard: its fixed address, its child process (None
    for a statically registered external shard), and its lifecycle
    counters."""

    __slots__ = ("name", "host", "port", "proc", "ckpt_dir",
                 "log_path", "restarts", "draining", "ready",
                 "started_unix")

    def __init__(self, name: str, host: str, port: int):
        self.name = name
        self.host = host
        self.port = int(port)
        self.proc: Optional[subprocess.Popen] = None
        self.ckpt_dir: Optional[str] = None
        self.log_path: Optional[str] = None
        self.restarts = 0
        self.draining = False
        self.ready = False
        self.started_unix = time.time()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def managed(self) -> bool:
        return self.proc is not None

    def row(self) -> Dict[str, Any]:
        return {"name": self.name, "addr": self.addr,
                "pid": self.proc.pid if self.proc is not None else None,
                "managed": self.managed, "alive": self.alive,
                "ready": self.ready, "draining": self.draining,
                "restarts": self.restarts,
                "uptime_s": round(time.time() - self.started_unix, 3)}


def _probe(host: str, port: int, payload: dict,
           timeout: float = 5.0) -> Optional[dict]:
    """One out-of-band request/response against a shard (readiness
    ping, attach probe).  Returns None on any connection/protocol
    failure — probing a dead shard is an expected, quiet event."""
    try:
        with socket.create_connection((host, port),
                                      timeout=timeout) as s:
            s.settimeout(timeout)
            f = s.makefile("rwb")
            f.write(json.dumps(payload, separators=(",", ":"))
                    .encode() + b"\n")
            f.flush()
            line = f.readline()
        return json.loads(line) if line else None
    except (OSError, ValueError):
        return None


class Router(WireServer):
    """The front-tier process: construct, ``start()`` (spawns and
    ring-joins the initial shards), point clients at ``.port``,
    ``stop()`` (drains the supervisor, then the shards).

    ``shards=0`` starts an empty tier for tests and external
    topologies — ``register()`` ring-joins already-running servers
    the supervisor never touches."""

    WIRE_NAME = "ut-route"
    SUPERVISE_INTERVAL = 1.0
    READY_TIMEOUT = 300.0       # shard cold start pays the jax import

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 shards: int = 2, *, shard_host: str = "127.0.0.1",
                 slots: int = 8, max_sessions: int = 256,
                 store_dir: Optional[str] = None,
                 work_dir: Optional[str] = None,
                 orphan_ttl: Optional[float] = None,
                 supervise_interval: Optional[float] = None,
                 hub_timeline: Optional[str] = None,
                 replicas: int = 64,
                 autoscale: Optional[Tuple[float, float]] = None,
                 autoscale_bounds: Tuple[int, int] = (1, 16)):
        super().__init__(host, port)
        self.shard_host = str(shard_host)
        self.slots = int(slots)
        self.max_sessions = int(max_sessions)
        self.work_dir = os.path.abspath(work_dir or os.getcwd())
        self.run_dir = os.path.join(self.work_dir, "ut.route")
        self.store_dir = ("off" if store_dir in (None, "", "off")
                          else os.path.abspath(str(store_dir)))
        self.orphan_ttl = orphan_ttl
        self.supervise_interval = float(
            supervise_interval if supervise_interval is not None
            else self.SUPERVISE_INTERVAL)
        # the embedded fleet collector every shard ships to; timeline
        # off by default (the router's view is live, not forensic)
        self.hub = TelemetryHub(host="127.0.0.1", port=0,
                                timeline=hub_timeline)
        self._ring = HashRing(replicas=replicas)
        self._shards: Dict[str, _Shard] = {}
        self._sessions: Dict[str, str] = {}     # sid -> shard name
        self._target = int(shards)
        self._next_idx = 0
        self._spawning = 0      # in-flight spawns (booting, unjoined)
        # load-driven target adjustment off the hub's per-shard
        # gauges: (lo, hi) mean-sessions-per-shard thresholds
        self.autoscale = (None if autoscale is None else
                          (float(autoscale[0]), float(autoscale[1])))
        self.autoscale_bounds = (int(autoscale_bounds[0]),
                                 int(autoscale_bounds[1]))
        self._scale_hold = 0.0  # no-flap cooldown (unix deadline)
        self._sup_stop = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None
        self.kills = 0          # route.kill injections fired

    # -- shard lifecycle -----------------------------------------------
    def _pick_port(self) -> int:
        s = socket.socket()
        s.bind((self.shard_host, 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _spawn_proc(self, shard: _Shard) -> None:
        """(Re)launch one shard child on its fixed port.  Never called
        under the router lock: Popen and the filesystem touches are
        blocking.  The ``route.spawn`` fault point can delay or fail
        the launch deterministically."""
        faults.fire("route.spawn")
        os.makedirs(shard.ckpt_dir, exist_ok=True)
        cmd = [sys.executable, "-m", "uptune_tpu.cli", "serve",
               "--host", self.shard_host,
               "--port", str(shard.port),
               "--slots", str(self.slots),
               "--max-sessions", str(self.max_sessions),
               "--store-dir", self.store_dir,
               "--work-dir", self.work_dir,
               "--durable", shard.ckpt_dir,
               "--telemetry", f"127.0.0.1:{self.hub.port}"]
        if self.orphan_ttl is not None:
            cmd += ["--orphan-ttl", str(self.orphan_ttl)]
        # children must NOT inherit the router's fault schedules: an
        # armed route.kill spec would re-arm inside every shard as an
        # unknown-point error at startup.  PYTHONPATH is wired so the
        # `-m uptune_tpu.cli` child imports from a plain checkout too
        # (utils/pypath.py — the fleet/failover bench idiom)
        from ..utils.pypath import child_pythonpath
        env = {k: v for k, v in os.environ.items()
               if k != faults.ENV_VAR}
        env["PYTHONPATH"] = child_pythonpath()
        lf = open(shard.log_path, "ab")
        try:
            shard.proc = subprocess.Popen(
                cmd, cwd=self.work_dir, env=env, stdout=lf,
                stderr=subprocess.STDOUT)
        finally:
            lf.close()      # the child holds its own fd now
        shard.ready = False
        shard.started_unix = time.time()
        log.info("[ut-route] shard %s -> pid %d on %s", shard.name,
                 shard.proc.pid, shard.addr)

    def _new_shard(self) -> _Shard:
        """Allocate the next shard record (name, fixed port, dirs)
        under the lock, without spawning."""
        with self._lock:
            name = f"s{self._next_idx}"
            self._next_idx += 1
        shard = _Shard(name, self.shard_host, self._pick_port())
        shard.ckpt_dir = os.path.join(self.run_dir, name, "ckpt")
        shard.log_path = os.path.join(self.run_dir, name + ".log")
        return shard

    def _reserve_spawn(self) -> bool:
        """Atomically claim one spawn slot iff the tier (live shards
        PLUS in-flight spawns) is still below target.  A booting
        shard joins ``_shards`` only once ready, so without this
        reservation the supervisor's converge tick and a concurrent
        ``scale`` caller each see "below target" during the boot and
        overshoot together."""
        with self._lock:
            live = sum(1 for sh in self._shards.values()
                       if not sh.draining)
            if live + self._spawning >= self._target:
                return False
            self._spawning += 1
            return True

    def _spawn_shard(self) -> _Shard:
        """Spawn one new shard and ring-join it once it answers ping.
        Blocking (cold start pays the engine import) — callers run on
        the worker pool or the supervisor thread, never the loop, and
        must hold a ``_reserve_spawn()`` slot."""
        try:
            shard = self._new_shard()
            self._spawn_proc(shard)
            self._wait_ready(shard)
            with self._lock:
                self._shards[shard.name] = shard
                self._ring.add(shard.name)
        finally:
            with self._lock:
                self._spawning -= 1
        obs.count("route.spawns")
        return shard

    def _wait_ready(self, shard: _Shard,
                    timeout: Optional[float] = None) -> None:
        deadline = time.time() + (timeout if timeout is not None
                                  else self.READY_TIMEOUT)
        while time.time() < deadline:
            if _probe(shard.host, shard.port, {"op": "ping"},
                      timeout=2.0) is not None:
                shard.ready = True
                return
            if not shard.alive:
                tail = ""
                try:
                    with open(shard.log_path, "rb") as f:
                        tail = f.read()[-2000:].decode("utf-8",
                                                       "replace")
                except OSError:
                    pass
                raise RuntimeError(
                    f"shard {shard.name} died before ready "
                    f"(rc={shard.proc.returncode}): {tail}")
            time.sleep(0.25)
        raise RuntimeError(f"shard {shard.name} never became ready "
                           f"on {shard.addr}")

    def register(self, host: str, port: int,
                 name: Optional[str] = None) -> str:
        """Ring-join an EXTERNAL already-running server the supervisor
        must not manage (tests, pre-spawned topologies).  Returns the
        shard name."""
        with self._lock:
            if name is None:
                name = f"s{self._next_idx}"
                self._next_idx += 1
            shard = _Shard(name, host, port)
            shard.ready = True
            self._shards[name] = shard
            self._ring.add(name)
            # registering grows the tier: without the target bump the
            # supervisor's converge step would immediately drain the
            # shard it was just handed
            self._target = max(
                self._target,
                sum(1 for sh in self._shards.values()
                    if not sh.draining))
        return name

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "Router":
        os.makedirs(self.run_dir, exist_ok=True)
        self.hub.start()
        super().start()
        # the initial tier comes up before start() returns, so a
        # caller may open sessions immediately (shards booting in
        # parallel would be faster; booting serially keeps the 1-core
        # CI box from thrashing K cold jax imports at once)
        while self._reserve_spawn():
            self._spawn_shard()
        self._sup_thread = threading.Thread(
            target=self._supervise, name="ut-route-sup", daemon=True)
        self._sup_thread.start()
        return self

    def stop(self) -> None:
        self._sup_stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=self.supervise_interval + 5)
        super().stop()
        with self._lock:
            shards = list(self._shards.values())
        for sh in shards:
            if sh.proc is not None and sh.proc.poll() is None:
                sh.proc.terminate()
        deadline = time.time() + 10
        for sh in shards:
            if sh.proc is None:
                continue
            while sh.proc.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if sh.proc.poll() is None:
                sh.proc.kill()
                sh.proc.wait()
        self.hub.stop()

    def _listen_banner(self) -> str:
        return (f" (shards={self._target}, hub=127.0.0.1:"
                f"{self.hub.port}, store={self.store_dir})")

    # -- supervisor -----------------------------------------------------
    def _supervise(self) -> None:
        """The control loop: deterministic kill injection, dead-shard
        respawn, target convergence, fleet-health gauges.  One tick
        must never die — a supervisor that exits silently turns every
        future shard death into a permanent outage."""
        while not self._sup_stop.wait(self.supervise_interval):
            try:
                self._tick()
            except Exception:
                log.exception("[ut-route] supervisor tick failed")

    def _tick(self) -> None:
        # 1) fault injection: `route.kill` armed with `error` makes
        # THIS tick SIGKILL the lowest-index live shard — the
        # deterministic stand-in for a shard host dying mid-bench
        try:
            faults.fire("route.kill")
        except faults.FaultInjected:
            self._kill_one()
        with self._lock:
            shards = list(self._shards.values())
            target = self._target
        # 2) reap + respawn: a dead managed shard comes back on the
        # SAME port with the SAME checkpoint dir, so `ut serve
        # --durable` recovery replays its sessions and resuming
        # clients reconnect to the address they already hold
        for sh in shards:
            if sh.managed and not sh.alive and not sh.draining:
                rc = sh.proc.returncode
                sh.restarts += 1
                log.warning("[ut-route] shard %s died (rc=%s); "
                            "respawning on %s (restart #%d)",
                            sh.name, rc, sh.addr, sh.restarts)
                obs.count("route.restarts")
                self._spawn_proc(sh)
            elif sh.managed and sh.alive and not sh.ready:
                if _probe(sh.host, sh.port, {"op": "ping"},
                          timeout=2.0) is not None:
                    sh.ready = True
                    log.info("[ut-route] shard %s ready on %s",
                             sh.name, sh.addr)
        # 3) converge toward target: spawn up, drain down (drained
        # shards leave the ring immediately — no NEW session routes
        # there — and keep serving their existing tenants)
        live = [sh for sh in shards if not sh.draining]
        if len(live) < target:
            if self._reserve_spawn():
                self._spawn_shard()
        elif len(live) > target:
            victim = max(live, key=lambda sh: sh.name)
            with self._lock:
                victim.draining = True
                self._ring.remove(victim.name)
            log.info("[ut-route] draining shard %s (target %d)",
                     victim.name, target)
        # 4) load-driven autoscaling (opt-in): the hub's per-shard
        # session gauges move the target inside the configured
        # bounds — spawn when the tier runs hot, drain when idle
        if self.autoscale is not None:
            self._autoscale()
        # 5) fleet gauges off the hub rollup (worst-first health is
        # one `health` op away for operators; the gauge is the cheap
        # always-on signal)
        with self._lock:
            n_live = sum(1 for sh in self._shards.values()
                         if not sh.draining)
        obs.gauge("route.shards", n_live)

    def _autoscale(self) -> None:
        """One autoscale decision off the hub's live rollup: mean
        sessions per live shard above `hi` raises the target by one,
        below `lo` lowers it by one (the converge step does the
        actual spawn/drain).  A cooldown of a few supervisor ticks
        lets each adjustment settle — the new shard must boot and
        take load — before the next, so the tier cannot flap."""
        lo, hi = self.autoscale
        if time.time() < self._scale_hold:
            return
        sess = self.hub.gauge_values("serve.sessions.active")
        if not sess:
            return
        with self._lock:
            n_live = sum(1 for sh in self._shards.values()
                         if not sh.draining)
            target = self._target
        if not n_live:
            return
        mean = sum(sess) / n_live
        nmin, nmax = self.autoscale_bounds
        new = target
        if mean > hi and target < nmax:
            new = target + 1
        elif mean < lo and target > nmin:
            new = target - 1
        if new == target:
            return
        with self._lock:
            self._target = new
        self._scale_hold = time.time() + 5 * self.supervise_interval
        obs.count("route.autoscale.up" if new > target
                  else "route.autoscale.down")
        log.info("[ut-route] autoscale: mean %.1f sessions/shard "
                 "(lo=%g, hi=%g) -> target %d", mean, lo, hi, new)

    def _kill_one(self) -> None:
        """SIGKILL the lowest-index live managed shard (the
        deterministic route.kill action)."""
        with self._lock:
            victims = sorted(
                (sh for sh in self._shards.values()
                 if sh.managed and sh.alive and not sh.draining),
                key=lambda sh: int(sh.name.lstrip("s") or 0))
        if not victims:
            return
        sh = victims[0]
        self.kills += 1
        obs.count("route.kills")
        log.warning("[ut-route] route.kill: SIGKILL shard %s "
                    "(pid %d)", sh.name, sh.proc.pid)
        try:
            sh.proc.send_signal(signal.SIGKILL)
        except OSError:
            pass

    # -- routing --------------------------------------------------------
    def _shard_for_key(self, key: str) -> _Shard:
        with self._lock:
            name = self._ring.lookup(key)
            shard = self._shards.get(name) if name else None
        if shard is None:
            raise RequestError("no shards available")
        return shard

    def _remember(self, sid: str, shard_name: str) -> None:
        with self._lock:
            self._sessions[sid] = shard_name
            while len(self._sessions) > SESSION_MAP_CAP:
                self._sessions.pop(next(iter(self._sessions)))

    # -- ops ------------------------------------------------------------
    def _op_ping(self, req: dict) -> dict:
        with self._lock:
            n = sum(1 for sh in self._shards.values()
                    if not sh.draining)
            mapped = len(self._sessions)
        return {"t": time.time(), "role": "router", "shards": n,
                "sessions": mapped}

    def _op_open(self, req: dict) -> dict:
        """Route one open: hash the declared space records onto the
        ring and redirect the client to the owning shard.  The shard
        itself validates the space and runs admission — the router
        only needs the records' bytes, so it never imports the
        engine."""
        records = req.get("space")
        if not isinstance(records, list) or not records:
            raise RequestError("open needs 'space': a non-empty list "
                               "of param records")
        key = routing_key(records)
        shard = self._shard_for_key(key)
        sid = req.get("session")
        if isinstance(sid, str) and sid:
            # a client-minted durable id (the auto-resume protocol):
            # remember its placement so a later attach through the
            # router skips the probe
            self._remember(sid, shard.name)
        obs.count("route.opens")
        return {"redirect": shard.addr, "shard": shard.name,
                "key": key[:12]}

    def _op_attach(self, req: dict) -> dict:
        """Route a re-attach: known ids redirect straight to their
        recorded shard; unknown ids (router restarted, map evicted)
        are found by probing each live shard's session registry —
        durable sessions survive ROUTER death too, not just shard
        death."""
        sid = req.get("session")
        if not isinstance(sid, str) or not sid:
            raise RequestError("attach needs 'session': a durable "
                               "session id")
        with self._lock:
            name = self._sessions.get(sid)
            shard = self._shards.get(name) if name else None
            candidates = [sh for sh in self._shards.values()
                          if sh.ready]
        if shard is None:
            for sh in candidates:
                resp = _probe(sh.host, sh.port,
                              {"op": "stats", "sessions": True})
                if resp and sid in (resp.get("session_ids") or ()):
                    shard = sh
                    self._remember(sid, sh.name)
                    break
        if shard is None:
            raise RequestError(f"unknown session: {sid}")
        obs.count("route.attaches")
        return {"redirect": shard.addr, "shard": shard.name}

    def _op_route(self, req: dict) -> dict:
        """Pure lookup (diagnostics, tests): key -> owning shard."""
        key = req.get("key")
        if not isinstance(key, str) or not key:
            records = req.get("space")
            if not isinstance(records, list) or not records:
                raise RequestError("route needs 'key' or 'space'")
            key = routing_key(records)
        shard = self._shard_for_key(key)
        return {"shard": shard.name, "addr": shard.addr,
                "key": key[:12]}

    # one resolve's fan-out bound: the request side is capped by the
    # wire max_line, but k tiny keys resolve to k shard rows
    MAX_RESOLVE = 1024

    def _op_resolve(self, req: dict) -> dict:
        """Multi-signature resolve (ISSUE 20): many space-record
        lists (``spaces``) or precomputed routing keys (``keys``) to
        their owning shards in ONE round trip — a client opening many
        sessions against the sharded tier maps them all first instead
        of paying one redirect RTT per open.  Element-wise error
        walls: one malformed entry yields an error ROW, the rest
        still resolve."""
        spaces = req.get("spaces")
        keys = req.get("keys")
        if spaces is not None:
            if not isinstance(spaces, list):
                raise RequestError("'spaces' must be a list of space "
                                   "record lists")
            entries: List[Any] = spaces
            use_keys = False
        elif keys is not None:
            if not isinstance(keys, list):
                raise RequestError("'keys' must be a list of routing "
                                   "keys")
            entries = keys
            use_keys = True
        else:
            raise RequestError("resolve needs 'spaces' or 'keys'")
        if len(entries) > self.MAX_RESOLVE:
            raise RequestError(
                f"resolve carries {len(entries)} entries; capped at "
                f"{self.MAX_RESOLVE}")
        rows: List[Dict[str, Any]] = []
        for ent in entries:
            try:
                if use_keys:
                    if not isinstance(ent, str) or not ent:
                        raise RequestError(
                            "routing key must be a non-empty string")
                    key = ent
                else:
                    if not isinstance(ent, list) or not ent:
                        raise RequestError(
                            "space records must be a non-empty list")
                    key = routing_key(ent)
                shard = self._shard_for_key(key)
                rows.append({"shard": shard.name, "addr": shard.addr,
                             "key": key[:12]})
            except RequestError as e:
                rows.append({"error": str(e)})
        obs.count("route.resolves", len(rows))
        return {"resolved": rows}

    def _op_shards(self, req: dict) -> dict:
        with self._lock:
            rows = [sh.row() for sh in self._shards.values()]
            target = self._target
        rows.sort(key=lambda r: r["name"])
        return {"target": target, "shards": rows}

    def _op_scale(self, req: dict) -> dict:
        """Move the shard target.  Scale-up spawns synchronously (the
        caller wants capacity NOW and runs on the worker pool);
        scale-down is handed to the supervisor, one drain per tick."""
        try:
            target = int(req["shards"])
        except (KeyError, TypeError, ValueError) as e:
            raise RequestError(f"scale needs 'shards': an int ({e})")
        if not 0 <= target <= 64:
            raise RequestError(f"shards must be in [0, 64]: {target}")
        with self._lock:
            self._target = target
        spawned = []
        while self._reserve_spawn():
            spawned.append(self._spawn_shard().name)
        # a concurrent supervisor tick may hold some of the spawns:
        # wait until the RING reaches target (scale-up is "capacity
        # now" — the caller must be able to route to K shards when
        # this returns), bounded by the cold-start budget
        deadline = time.time() + self.READY_TIMEOUT
        while True:
            with self._lock:
                live = sum(1 for sh in self._shards.values()
                           if not sh.draining)
            if live >= target:
                break
            if time.time() > deadline:
                raise RequestError(
                    f"scale to {target} timed out at {live} live "
                    f"shard(s)")
            time.sleep(0.1)
        return {"target": target, "live": live, "spawned": spawned}

    def _op_metrics(self, req: dict) -> dict:
        """The fleet scrape, `ut top --addr <router>` shaped: the
        hub's rollup with the per-process population gauges
        re-aggregated as SUMS over live shards (last-write-wins is
        wrong for ``serve.sessions.active`` — five shards serving 40
        tenants each is 200 sessions, not 40)."""
        out = self.hub._op_metrics({})
        gauges = out["metrics"].setdefault("gauges", {})
        sess = self.hub.gauge_values("serve.sessions.active")
        if sess:
            gauges["serve.sessions.active"] = float(sum(sess))
        fills = self.hub.gauge_values("serve.batch_fill")
        if fills:
            gauges["serve.batch_fill"] = float(
                sum(fills) / len(fills))
        with self._lock:
            n_live = sum(1 for sh in self._shards.values()
                         if not sh.draining)
        out["sessions"] = int(sum(sess)) if sess else 0
        out["shards"] = n_live
        out["uptime_s"] = round(time.time() - self.started_unix, 3)
        return out

    def _op_sources(self, req: dict) -> dict:
        """The hub's per-source rows, annotated with the owning shard
        name by pid (`ut top --fleet` renders one row per shard)."""
        out = self.hub._op_sources(req)
        with self._lock:
            by_pid = {str(sh.proc.pid): sh.name
                      for sh in self._shards.values()
                      if sh.proc is not None}
        for row in out.get("rows", ()):
            name = by_pid.get(str(row.get("pid")))
            if name:
                row["shard"] = name
        return out

    def _op_health(self, req: dict) -> dict:
        """Worst-first fleet health: the hub's source verdicts plus
        the supervisor's shard liveness rows."""
        out = self.hub._op_health(req)
        with self._lock:
            rows = [sh.row() for sh in self._shards.values()]
        rows.sort(key=lambda r: r["name"])
        out["shards"] = rows
        return out

    def _op_stats(self, req: dict) -> dict:
        with self._lock:
            rows = [sh.row() for sh in self._shards.values()]
            mapped = len(self._sessions)
            target = self._target
        rows.sort(key=lambda r: r["name"])
        return {"shards": rows, "target": target,
                "sessions_mapped": mapped, "kills": self.kills,
                "restarts": sum(r["restarts"] for r in rows),
                "hub": self.hub._op_stats({})}

    _OPS = {"ping": _op_ping, "open": _op_open, "attach": _op_attach,
            "route": _op_route, "resolve": _op_resolve,
            "shards": _op_shards,
            "scale": _op_scale, "metrics": _op_metrics,
            "sources": _op_sources, "health": _op_health,
            "stats": _op_stats}


# ------------------------------------------------------------------ CLI
def build_parser():
    import argparse
    p = argparse.ArgumentParser(
        prog="ut route",
        description="uptune-tpu sharded front tier: consistent-hash "
                    "session router over K `ut serve --durable` "
                    "shards (docs/SERVING.md 'Sharded front tier')")
    p.add_argument("--host", default="127.0.0.1",
                   help="router bind address")
    p.add_argument("--port", type=int, default=8777,
                   help="router TCP port; 0 picks an ephemeral port")
    p.add_argument("--shards", type=int, default=2, metavar="K",
                   help="initial shard-process count (the `scale` op "
                        "moves it at runtime)")
    p.add_argument("--shard-host", default="127.0.0.1",
                   help="bind address for shard children")
    p.add_argument("--slots", type=int, default=8,
                   help="per-shard engine-group slot width")
    p.add_argument("--max-sessions", type=int, default=256,
                   help="per-shard admission limit")
    p.add_argument("--store-dir", default=None, metavar="DIR",
                   help="SHARED cross-tenant result memo all shards "
                        "mount; 'off'/unset disables")
    p.add_argument("--work-dir", default=None,
                   help="base dir for shard state (ut.route/ holds "
                        "per-shard checkpoint dirs and logs)")
    p.add_argument("--orphan-ttl", type=float, default=None,
                   metavar="SECONDS",
                   help="per-shard disconnected-tenant grace "
                        "(ut serve --orphan-ttl)")
    p.add_argument("--supervise-interval", type=float, default=None,
                   metavar="SECONDS",
                   help="supervisor tick cadence (default 1.0)")
    p.add_argument("--hub-timeline", default=None, metavar="OUT.jsonl",
                   help="persist the embedded hub's fleet timeline "
                        "(default: off)")
    p.add_argument("--autoscale", default=None, metavar="LO:HI",
                   help="load-driven shard autoscaling: when mean "
                        "sessions per live shard (embedded-hub "
                        "serve.sessions.active gauges) exceeds HI the "
                        "supervisor spawns a shard, below LO it "
                        "drains one (default: off; static target)")
    p.add_argument("--autoscale-max", type=int, default=16, metavar="N",
                   help="upper shard-count bound for --autoscale")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="[%(relativeCreated)7.0fms] %(levelname)s %(message)s")
    # UT_FAULTS (obs/faults.py): route.kill / route.spawn schedules
    # for the sharded failover bench — never a production mode
    n_faults = faults.maybe_arm_from_env()
    if n_faults:
        log.warning("[ut-route] %d fault-injection rule(s) ARMED via "
                    "UT_FAULTS: %s", n_faults, faults.schedules())
    autoscale = None
    if args.autoscale:
        try:
            lo_s, hi_s = args.autoscale.split(":", 1)
            autoscale = (float(lo_s), float(hi_s))
            if not autoscale[0] < autoscale[1]:
                raise ValueError
        except ValueError:
            build_parser().error(
                "--autoscale wants LO:HI with LO < HI, got %r"
                % args.autoscale)
    r = Router(host=args.host, port=args.port, shards=args.shards,
               shard_host=args.shard_host, slots=args.slots,
               max_sessions=args.max_sessions,
               store_dir=args.store_dir, work_dir=args.work_dir,
               orphan_ttl=args.orphan_ttl,
               supervise_interval=args.supervise_interval,
               hub_timeline=args.hub_timeline,
               autoscale=autoscale,
               autoscale_bounds=(1, args.autoscale_max))
    r.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
