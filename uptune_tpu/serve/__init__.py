"""Tuning-as-a-service: a multi-tenant session server (docs/SERVING.md).

The reference shipped result *transport* (ZMQ result pipes, S3 archive
push — PAPER.md L1/L5) but never a serving plane: every tune is a
process.  This package is the serving plane — ONE persistent process
multiplexing thousands of concurrent ask/tell tuning sessions onto the
batched engine:

* **Sessions are versioned snapshots** (the PR 5 pattern): ``ask``
  hands out tickets against the session's current published state
  version; ``tell`` fills the measured batch, and the commit that
  completes it publishes the next version.  Stale tickets are rejected,
  never silently merged.
* **Proposal generation batches ACROSS tenants**: sessions whose spaces
  share one structural signature are packed onto one
  ``BatchedEngine`` instance axis, so one vmapped dispatch proposes for
  every needy tenant at once (same compiled program; join/leave is
  instance-slot allocation over donate-in-place stacked state and never
  retraces — engine/batched.py slot primitives).
* **The store is a shared cross-tenant memo**: every session scope
  mounts the content-addressed result store, so a configuration one
  tenant measured is served to any other tenant's ask without a build.

Surface: ``ut serve`` (CLI), ``uptune_tpu.connect()`` -> SessionClient
(wire protocol: newline-delimited JSON over TCP), and ``LocalSession``
— the same session mechanics without a server, which doubles as the
matched-seed offline sibling the parity tests hold the server to.
"""
# Lazy surface (the uptune_tpu/__init__ pattern): the wire kernel
# (serve/wire.py) and its light consumers — the fleet-telemetry hub,
# `ut top`'s poller, SessionClient — must stay importable without
# paying the engine/jax import the session modules pull in.
_LAZY = {
    "SessionClient": ("uptune_tpu.serve.client", "SessionClient"),
    "SessionHandle": ("uptune_tpu.serve.client", "SessionHandle"),
    "ServeError": ("uptune_tpu.serve.client", "ServeError"),
    "ConnectionLostError": ("uptune_tpu.serve.client",
                            "ConnectionLostError"),
    "CheckpointLog": ("uptune_tpu.serve.durable", "CheckpointLog"),
    "SessionRestoredError": ("uptune_tpu.serve.session",
                             "SessionRestoredError"),
    "Trial": ("uptune_tpu.serve.client", "Trial"),
    "connect": ("uptune_tpu.serve.client", "connect"),
    "SessionGroup": ("uptune_tpu.serve.group", "SessionGroup"),
    "group_key": ("uptune_tpu.serve.group", "group_key"),
    "LocalSession": ("uptune_tpu.serve.session", "LocalSession"),
    "Session": ("uptune_tpu.serve.session", "Session"),
    "StaleTicketError": ("uptune_tpu.serve.session", "StaleTicketError"),
    "SessionServer": ("uptune_tpu.serve.server", "SessionServer"),
    "RequestError": ("uptune_tpu.serve.wire", "RequestError"),
    "WireServer": ("uptune_tpu.serve.wire", "WireServer"),
    "WireReply": ("uptune_tpu.serve.wire", "WireReply"),
    "encode_reply": ("uptune_tpu.serve.wire", "encode_reply"),
    "Router": ("uptune_tpu.serve.router", "Router"),
    "HashRing": ("uptune_tpu.serve.router", "HashRing"),
    "routing_key": ("uptune_tpu.serve.router", "routing_key"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    value = getattr(importlib.import_module(mod_name), attr)
    globals()[name] = value     # cache: resolve once
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
