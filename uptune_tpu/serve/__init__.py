"""Tuning-as-a-service: a multi-tenant session server (docs/SERVING.md).

The reference shipped result *transport* (ZMQ result pipes, S3 archive
push — PAPER.md L1/L5) but never a serving plane: every tune is a
process.  This package is the serving plane — ONE persistent process
multiplexing thousands of concurrent ask/tell tuning sessions onto the
batched engine:

* **Sessions are versioned snapshots** (the PR 5 pattern): ``ask``
  hands out tickets against the session's current published state
  version; ``tell`` fills the measured batch, and the commit that
  completes it publishes the next version.  Stale tickets are rejected,
  never silently merged.
* **Proposal generation batches ACROSS tenants**: sessions whose spaces
  share one structural signature are packed onto one
  ``BatchedEngine`` instance axis, so one vmapped dispatch proposes for
  every needy tenant at once (same compiled program; join/leave is
  instance-slot allocation over donate-in-place stacked state and never
  retraces — engine/batched.py slot primitives).
* **The store is a shared cross-tenant memo**: every session scope
  mounts the content-addressed result store, so a configuration one
  tenant measured is served to any other tenant's ask without a build.

Surface: ``ut serve`` (CLI), ``uptune_tpu.connect()`` -> SessionClient
(wire protocol: newline-delimited JSON over TCP), and ``LocalSession``
— the same session mechanics without a server, which doubles as the
matched-seed offline sibling the parity tests hold the server to.
"""
from .client import SessionClient, SessionHandle, ServeError, Trial, connect  # noqa: F401
from .group import SessionGroup, group_key  # noqa: F401
from .session import LocalSession, Session, StaleTicketError  # noqa: F401
from .server import SessionServer  # noqa: F401
