"""Engine groups: many tenants, one batched program.

A ``SessionGroup`` owns one ``BatchedEngine`` whose instance axis is a
pool of *slots*.  Each tenant session occupies one slot; everything a
session does maps onto exactly three compiled programs, each traced
once for the group's lifetime (the strict trace-guard contract):

* join / slot reuse -> ``jit_init_slot``   (donated, dynamic index)
* need proposals    -> ``jit_propose_all`` (ONE vmapped dispatch for
  every slot — the cross-tenant batching this plane exists for)
* batch measured    -> ``jit_commit_slot`` (donated, dynamic index)

Proposal epochs exploit that ``propose`` is pure in the state: an
epoch taken now is valid for every slot that has not committed since,
so one dispatch refreshes every needy tenant (``pending_for``
coalesces), and a mid-flight tenant keeps its older epoch — the
stacked arrays it will commit against stay alive by reference.

All group state is guarded by one reentrant lock per group.  The
propose is ENQUEUED under that lock but never awaited there: JAX
dispatch is asynchronous, so the lock covers microseconds of argument
processing while the vmapped compute runs on the runtime's own
threads — the blocking device->host read happens later, in
``ProposalEpoch.host_rows``, outside the lock.  Enqueueing under the
lock is also what makes the donation discipline sound: commit_slot
and init_slot DONATE the stacked state, and they take the same lock,
so a propose's input buffers can never be invalidated between
snapshotting the state and dispatching on it (once both are enqueued,
the runtime sequences the in-flight read before the donated write).
A commit landing after the propose only makes the published epoch
stale for THAT slot — its generation moved — which triggers the next
refresh.

The three slot programs are traced + compiled at GROUP CONSTRUCTION
(one warmup propose/commit/init round on placeholder slot 0): a
serving group pays compile at onboarding, never inside a tenant's ask
— BENCH_SERVE's single-digit-ms ask p95 depends on it.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..engine import BatchedEngine, FusedEngine
from ..space.spec import CandBatch, Space


def group_key(space: Space, arms: Optional[Sequence[str]],
              sense: str, history_capacity: int) -> Tuple:
    """Tenant-grouping identity: sessions are multiplexed onto one
    batched program only when EVERYTHING that shapes its avals and
    semantics matches — structural space signature, arm portfolio,
    orientation, and dedup-history capacity."""
    return (tuple(space.signature()),
            tuple(arms) if arms else "default",
            sense, int(history_capacity))


class ProposalEpoch:
    """One jit_propose_all output: stacked technique states, stacked
    candidates, stacked keys, plus the per-slot state generation at
    take time (validity check) and a lazily materialized host copy of
    the candidate rows (ONE device->host transfer per epoch; per-slot
    reads are numpy views)."""

    __slots__ = ("tstates", "cands", "keys", "slot_gens", "_host")

    def __init__(self, tstates, cands: CandBatch, keys,
                 slot_gens: Tuple[int, ...]):
        self.tstates = tstates
        self.cands = cands
        self.keys = keys
        self.slot_gens = slot_gens
        self._host = None

    def host_rows(self, slot: int) -> CandBatch:
        """Slot `slot`'s candidate batch as host numpy (for config
        decode); the stacked pull happens once per epoch.  Called
        WITHOUT the group lock (session decode runs unlocked), so the
        lazy materialization is one atomic tuple rebind — a racing
        duplicate pull is benign (identical values, last ref wins)."""
        h = self._host
        if h is None:
            h = (np.asarray(self.cands.u),
                 tuple(np.asarray(p) for p in self.cands.perms))
            self._host = h
        u, perms = h
        return CandBatch(u[slot], tuple(p[slot] for p in perms))


class SessionGroup:
    """One space signature's slice of the serving plane: a slot pool
    over a BatchedEngine plus the shared proposal-epoch cache."""

    def __init__(self, space: Space, slots: int, *,
                 arms: Optional[Sequence[str]] = None,
                 sense: str = "min", history_capacity: int = 1 << 10):
        self.space = space
        self.sense = sense
        self.key = group_key(space, arms, sense, history_capacity)
        # objective=None: evaluation is the TENANT's side of the
        # protocol — only the propose/commit halves ever run here, and
        # commit takes the measured raw batch directly
        self.engine = FusedEngine(space, None, arms=list(arms) if arms
                                  else None, sense=sense,
                                  history_capacity=history_capacity)
        self.batched = BatchedEngine(self.engine, slots)
        self.n_slots = int(slots)
        self.batch = self.engine.total_batch   # rows per epoch
        self.lock = threading.RLock()
        import jax
        # slot 0..n-1 placeholder streams; every join re-seeds its slot
        # from the tenant's own seed, so this key is inert — and a
        # constant one keeps group construction deterministic
        placeholder = jax.random.PRNGKey(0)
        self.state = self.batched.init(placeholder)
        self._jnp = jax.numpy
        self.slot_gen = [0] * self.n_slots
        self.free: List[int] = list(range(self.n_slots - 1, -1, -1))
        self.active: Dict[int, Any] = {}   # slot -> Session
        self.epoch: Optional[ProposalEpoch] = None
        self._warm(placeholder)

    def _warm(self, key) -> None:
        """Trace + compile the group's three programs up front with one
        throwaway propose/commit/init round on placeholder slot 0 (the
        commit's NaN batch and the init key are inert: every join
        re-seeds its slot before proposals are read).  Onboarding a new
        group pays the compile wall here — visible in BENCH_SERVE's
        open phase — so no tenant's ask ever does."""
        import jax
        with obs.span("serve.warm_compile", slots=self.n_slots):
            t, c, k = self.batched.jit_propose_all()(self.state)
            st = self.batched.jit_commit_slot()(
                self.state, t, c, k,
                self._jnp.full((self.batch,), self._jnp.nan,
                               self._jnp.float32),
                self._jnp.int32(0))
            self.state = self.batched.jit_init_slot()(
                st, self._jnp.int32(0), key)
            jax.block_until_ready(self.state)

    # -- membership ----------------------------------------------------
    @property
    def n_active(self) -> int:
        return len(self.active)

    @property
    def n_free(self) -> int:
        return len(self.free)

    def join(self, seed: int, store=None, session_id: Optional[str] = None):
        """Allocate a slot and seed it from the tenant's own PRNG
        stream (slot-position independent: the same seed produces the
        same session in ANY group — the matched-seed parity contract
        with LocalSession).  Raises IndexError when full."""
        from .session import Session
        import jax
        with self.lock:
            slot = self.free.pop()
            self.state = self.batched.jit_init_slot()(
                self.state, self._jnp.int32(slot),
                jax.random.PRNGKey(int(seed)))
            self.slot_gen[slot] += 1
            sess = Session(self, slot, int(seed), store=store,
                           session_id=session_id)
            self.active[slot] = sess
            obs.count("serve.joins")
            return sess

    def leave(self, sess) -> None:
        """Free the slot.  The departed tenant's state rows stay in the
        stacked arrays until a future join overwrites them (init_slot);
        proposals for free slots are dead rows nobody reads."""
        with self.lock:
            if self.active.get(sess.slot) is sess:
                del self.active[sess.slot]
                self.free.append(sess.slot)
                obs.count("serve.leaves")

    # -- the three device paths ----------------------------------------
    def pending_for(self, sess) -> ProposalEpoch:
        """An epoch valid for `sess`'s slot.  When the cached epoch
        predates the slot's last commit, ONE vmapped dispatch refreshes
        it — and with it every other needy tenant (coalescing: the
        batch-fill gauge records how many sessions each dispatch
        actually served).  The whole check-refresh-publish is one lock
        hold (enqueue only — see the module docstring); the first
        caller after a commit dispatches, everyone else reads the
        published epoch."""
        with self.lock:
            ep = self.epoch
            if ep is not None and \
                    ep.slot_gens[sess.slot] == self.slot_gen[sess.slot]:
                return ep
            needy = sum(1 for s in self.active.values()
                        if s.pending is None)
            with obs.span("serve.propose", slots=self.n_slots):
                t, c, k = self.batched.jit_propose_all()(self.state)
            ep = ProposalEpoch(t, c, k, tuple(self.slot_gen))
            self.epoch = ep
            obs.count("serve.proposes")
            obs.gauge("serve.batch_fill",
                      needy / max(1, self.n_slots))
            return ep

    def commit(self, sess, epoch: ProposalEpoch,
               raw: np.ndarray) -> None:
        """Publish `sess`'s measured epoch: one donated dispatch
        updating only its slot row of the stacked state."""
        with obs.span("serve.commit", slot=sess.slot):
            self.state = self.batched.jit_commit_slot()(
                self.state, epoch.tstates, epoch.cands, epoch.keys,
                self._jnp.asarray(raw, self._jnp.float32),
                self._jnp.int32(sess.slot))
        self.slot_gen[sess.slot] += 1
        obs.count("serve.commits")
