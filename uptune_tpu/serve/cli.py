"""`ut serve` — run the session server from the command line.

    ut serve                          # serve-host:serve-port defaults
    ut serve --port 0                 # ephemeral port (printed)
    ut serve --slots 256 --store-dir /shared/ut-store
    ut serve --trace serve_trace.json # obs plane export on shutdown

Flag precedence is the repo-wide contract: CLI flags > ut.config
(`serve-*` keys) > DEFAULTS (api/session.py) — tested in
tests/test_serve.py next to the store/trace key tests.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
from typing import List, Optional

log = logging.getLogger("uptune_tpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ut serve",
        description="uptune-tpu multi-tenant tuning session server "
                    "(docs/SERVING.md)")
    p.add_argument("--host", default=None,
                   help="bind address (default: ut.config serve-host)")
    p.add_argument("--port", type=int, default=None,
                   help="TCP port; 0 picks an ephemeral port "
                        "(default: ut.config serve-port)")
    p.add_argument("--slots", type=int, default=None,
                   help="instance-slot capacity per engine group: "
                        "sessions sharing a space signature batch "
                        "their proposal generation across one "
                        "BatchedEngine instance axis of this width "
                        "(default: ut.config serve-slots)")
    p.add_argument("--max-sessions", type=int, default=None,
                   help="admission limit across all groups "
                        "(default: ut.config serve-max-sessions)")
    p.add_argument("--store-dir", default=None, metavar="DIR",
                   help="shared cross-tenant result memo directory; "
                        "'off' disables (default: ut.config "
                        "serve-store-dir, else ut.serve/store under "
                        "the cwd)")
    p.add_argument("--work-dir", default=None,
                   help="base dir for the default store location")
    p.add_argument("--durable", nargs="?", const="on", default=None,
                   metavar="DIR",
                   help="crash-safe serving (docs/SERVING.md "
                        "'Durability & failover'): journal every "
                        "committed session transition to per-session "
                        "checkpoint segments (DIR, or "
                        "<store-dir>/checkpoints when omitted) and "
                        "recover all live sessions on startup — "
                        "SIGKILL loses zero committed tells.  'off' "
                        "disables (default: ut.config serve-durable)")
    p.add_argument("--durable-fsync", action="store_true",
                   default=None,
                   help="fsync each checkpoint append: committed "
                        "tells additionally survive power loss "
                        "(SIGKILL durability needs no fsync; default: "
                        "ut.config serve-durable-fsync)")
    p.add_argument("--workers", type=int, default=None, metavar="N",
                   help="wire-kernel worker-pool width: how many "
                        "requests may execute concurrently across "
                        "ALL connections (default 8).  The asyncio "
                        "connection loop itself is single-threaded; "
                        "workers are where commits and checkpoint "
                        "appends run")
    p.add_argument("--orphan-ttl", type=float, default=None,
                   metavar="SECONDS",
                   help="grace a disconnected durable tenant gets "
                        "before its slot is swept (default 900); "
                        "resuming clients re-attach inside it")
    p.add_argument("--trace", default=None, metavar="OUT.json",
                   help="observability export written at shutdown "
                        "(docs/OBSERVABILITY.md); 'off' disables. "
                        "Flushed on SIGINT/SIGTERM too, and the "
                        "metrics sidecar is a flight-recorder "
                        "timeline while the server runs")
    p.add_argument("--metrics-interval", type=float, default=None,
                   metavar="SECONDS",
                   help="flight-recorder cadence for the traced "
                        "server's metrics timeline (default 1.0; 0 "
                        "disables).  `ut top --metrics "
                        "OUT.json.metrics.jsonl` tails it live")
    p.add_argument("--metrics-rotate", type=int, default=None,
                   metavar="N",
                   help="flight-recorder rotation depth: generations "
                        "kept past the row cap (default 1)")
    p.add_argument("--telemetry", default=None, metavar="HOST:PORT",
                   help="ship this server's metrics windows, journal "
                        "rows, alerts AND its `{\"op\": \"health\"}` "
                        "session rollup to a running `ut hub` "
                        "collector (docs/OBSERVABILITY.md 'Fleet "
                        "telemetry').  Also reachable via "
                        "UT_TELEMETRY or ut.config({'telemetry': "
                        "...}); 'off' disables")
    p.add_argument("--journal", default=None, metavar="OUT.jsonl",
                   help="tuning journal (docs/OBSERVABILITY.md "
                        "'Search-quality telemetry'): one JSONL row "
                        "per session tell, plus the live "
                        "convergence/calibration gauges derived from "
                        "them; render post-hoc with `ut report`.  "
                        "Also reachable via UT_JOURNAL; 'off' "
                        "disables")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def resolve_config(args: argparse.Namespace) -> dict:
    """Flags > ut.config serve-* keys > DEFAULTS, resolved into the
    SessionServer constructor kwargs (None = let the constructor read
    the settings layer; the indirection exists so the precedence is
    testable without binding a socket)."""
    from ..api.session import settings
    out = {}
    for flag, key in (("host", "serve-host"), ("port", "serve-port"),
                      ("slots", "serve-slots"),
                      ("max_sessions", "serve-max-sessions"),
                      ("store_dir", "serve-store-dir"),
                      ("durable", "serve-durable"),
                      ("durable_fsync", "serve-durable-fsync")):
        v = getattr(args, flag)
        out[flag] = settings[key] if v is None else v
    out["work_dir"] = args.work_dir
    out["orphan_ttl"] = args.orphan_ttl
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="[%(relativeCreated)7.0fms] %(levelname)s %(message)s")

    # the proposal engine is cheap next to tenant builds; like the
    # tuning CLI, default to the hang-proof host platform
    from ..utils.platform_guard import force_cpu
    force_cpu(1)

    from .. import obs
    trace_path = args.trace
    if trace_path is None:
        trace_path = obs.maybe_enable_from_env()
        if trace_path is None and not obs.enabled():
            from ..api.session import settings
            cfg_trace = settings["trace"]
            if cfg_trace and str(cfg_trace).lower() not in ("off",
                                                            "none"):
                trace_path = str(cfg_trace)
    elif trace_path.lower() in ("off", "none"):
        trace_path = None
    if trace_path and not obs.enabled():
        obs.enable()
    # UT_DEVICE_TRACE=<dir>: programmatic jax.profiler capture for the
    # serving process (ISSUE 13) — stopped in the shutdown finally so
    # a SIGINT'd server still settles its XPlane dump
    dtrace = obs.device.maybe_trace_from_env()
    if trace_path:
        # a serving process is exactly the shape the flight recorder
        # exists for: long-lived, scraped rarely, killed by signal —
        # without the timeline + exit flush it leaves no telemetry
        obs.install_exit_flush(trace_path,
                               extra={"process": "ut-serve"})
        mi = (args.metrics_interval if args.metrics_interval is not None
              else 1.0)
        if mi > 0:
            obs.start_flight_recorder(
                trace_path, interval=mi,
                rotate=(args.metrics_rotate
                        if args.metrics_rotate is not None
                        else obs.flight.DEFAULT_ROTATE))

    # tuning journal (ISSUE 12): per-tenant serve_tell rows + the
    # derived search-quality gauges (which the metrics op and `ut top`
    # then expose).  Flag > UT_JOURNAL env; 'off' disables
    journal_path = args.journal
    if journal_path is None:
        mon = obs.maybe_journal_from_env()
        journal_path = obs.journal.path() if mon is not None else None
    elif obs.journal.disabled_token(journal_path):
        # same disable vocabulary as the tuning CLI / UT_JOURNAL
        journal_path = None
        mon = None
    else:
        mon = obs.start_journal(journal_path,
                                meta={"process": "ut-serve"})
    if journal_path and not trace_path:
        # journal without trace: SIGINT/SIGTERM must still flush the
        # buffered journal tail (and unwind into the finally below)
        obs.install_exit_flush(None)

    # UT_FAULTS (obs/faults.py): deterministic crash/delay/error
    # schedules for failover tests and `bench.py --failover` — a
    # production server never sets this; log loudly when armed
    n_faults = obs.faults.maybe_arm_from_env()
    if n_faults:
        log.warning("[ut-serve] %d fault-injection rule(s) ARMED via "
                    "UT_FAULTS: %s", n_faults, obs.faults.schedules())

    from .server import SessionServer
    srv = SessionServer(**resolve_config(args))
    if args.workers is not None and args.workers > 0:
        srv.max_workers = int(args.workers)

    # fleet telemetry (docs/OBSERVABILITY.md "Fleet telemetry"): flag
    # > UT_TELEMETRY env > ut.config('telemetry').  The serving
    # process additionally ships its session-health rollup, so the
    # hub's `health` op sees per-tenant verdicts fleet-wide
    shipper = None
    telemetry = args.telemetry
    if telemetry is None:
        telemetry = os.environ.get("UT_TELEMETRY", "").strip() or None
        if telemetry is None:
            from ..api.session import settings
            cfg_t = settings["telemetry"]
            if not obs.ship.disabled_token(cfg_t):
                telemetry = str(cfg_t)
    if obs.ship.disabled_token(telemetry):
        telemetry = None
    if telemetry:
        shipper = obs.ship.start(
            telemetry, role="ut-serve",
            health_provider=lambda: srv._op_health({}))
        # telemetry-only servers (no --trace/--journal) still need
        # the SIGINT/SIGTERM hooks: the exit flush's ship.stop()
        # ships the final window, and the chained handler unwinds
        # serve_forever into the finally below (idempotent)
        obs.install_exit_flush(None)

    try:
        srv.serve_forever()
    finally:
        if shipper is not None:
            shipper.stop()
            st = shipper.stats()
            log.info("[ut-serve] telemetry shipped to %s:%s (%d rows "
                     "acked, %d dropped)", shipper.addr[0],
                     shipper.addr[1], st["acked"], st["dropped"])
        if dtrace:
            obs.device.stop_trace()
            log.info("[ut-serve] device profile captured under %s",
                     dtrace)
        if journal_path:
            obs.stop_journal(mon)
            log.info("[ut-serve] journal written to %s (render with "
                     "`ut report %s`)", journal_path, journal_path)
        if trace_path:
            obs.finish(trace_path, extra={"process": "ut-serve"})
            log.info("[ut-serve] trace written to %s", trace_path)
        elif obs.enabled():
            snap = obs.metrics_snapshot()
            log.info("[ut-serve] final metrics: %s",
                     json.dumps(snap.get("counters", {})))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
