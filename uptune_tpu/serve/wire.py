"""The newline-JSON/TCP service kernel: one reusable server loop for
every wire-speaking plane in the repo.

PR 8's session server, the fleet-telemetry hub (obs/hub.py, ISSUE 14),
the sharded front-tier router (serve/router.py, ISSUE 17) and the
planes ROADMAP item 2 specifies against this seam (a remote
ResultStore server) all speak the same protocol: one JSON object per
line, each carrying an ``op`` field, answered by one JSON object per
line.  This module owns the generic half so each service only writes
its op table:

* **Dispatch** — a class-level ``_OPS`` table maps op names to
  handler methods; ``handle(request) -> response`` is transport-free
  (tests and in-process benches drive it directly) and never raises:
  a ``RequestError`` comes back as ``ok=False`` with the message, any
  other exception is caught by the defensive per-op error wall and
  reported as ``internal:`` — one misbehaving client can never take
  the serving loop down.  An optional ``id`` field is echoed verbatim
  so clients may pipeline; an optional ``ctx`` span id is recorded as
  the handler span's ``parent`` so `ut-trace merge` joins
  client/server shards (docs/OBSERVABILITY.md).
* **Batch frames** (ISSUE 20) — ``{"op": "batch", "ops": [...]}`` is
  handled by the kernel itself, so every wire-speaking service
  (``ut serve``, ``ut store``, ``ut hub``, ``ut route``) inherits it
  without touching its op table: one socket read, one dispatch walk,
  an ORDERED reply list written back as one coalesced send.  Each
  sub-op keeps its own error wall — a malformed sub-op yields an
  error *entry* in ``replies``, never a poisoned frame or connection
  — and the whole frame is bounded by the same ``max_line`` cap as a
  single request (one clean oversize error, then close).  Frames do
  not nest, and ``max_batch_ops`` bounds reply amplification.
* **Encode fast path** (ISSUE 20) — one module-cached
  ``JSONEncoder`` serializes every reply (the obs/journal precedent:
  ``json.dumps`` re-resolves its options per call), and a handler may
  return a ``WireReply`` carrying its own preserialized wire text
  (built from per-epoch cached canonical config JSON on the session
  server's ask path) — the connection loop writes that text verbatim
  and a batch frame splices sub-reply texts instead of re-encoding
  k configs per k-wide ask.
* **Connection plane** — since ISSUE 17 a single asyncio event loop
  (one ``-loop`` thread) owns accept + read + write for EVERY
  connection, replacing the thread-per-connection loops whose GIL
  handoffs were the ~1.7k asks/s ceiling (ROADMAP item 1): the loop
  never runs handler code — each parsed request is dispatched onto a
  BOUNDED worker pool (``max_workers``), so one slow commit stalls
  one worker, never the loop, and ten thousand idle tenants cost ten
  thousand coroutines instead of ten thousand threads.  Requests on
  one connection still complete in order (the coroutine awaits each
  dispatch), so per-connection semantics are exactly the old ones.
* **Per-connection state hooks** (``_conn_opened`` / ``_on_response``
  / ``_conn_closed``) let a service scope resources to the connection
  that created them and reap them when it dies — the session server's
  crashed-tenant slot reaping and the hub's source liveness both ride
  this seam, unchanged across the event-loop rewrite.
* **Hardening** — ``max_line`` caps one request line (one error
  reply, then close: the unread stream cannot be re-synchronized);
  ``idle_timeout`` bounds how long a silent connection may pin its
  coroutine.  Generous by default because serve tenants legitimately
  idle across external builds — instances may override either before
  ``start()``.
* **Reaping and shutdown** — dead connections prune themselves from
  the registry (long-lived servers stay bounded by LIVE connections
  under churn); ``stop()`` is a real barrier: the loop closes the
  listener and every connection, conn coroutines run their close
  hooks, and the loop thread is joined (bounded) so no handler races
  interpreter teardown writing to closed sockets.

Subclass contract::

    class MyServer(WireServer):
        WIRE_NAME = "my-server"          # log prefix + thread names
        def _op_ping(self, req): return {"t": time.time()}
        _OPS = {"ping": _op_ping}

``HANDLE_SPAN`` stays ``serve.handle`` for every service: the trace
merge tool joins ``client.request`` spans against that name, and a
hub or router is as much a serving plane as the session server.
"""
from __future__ import annotations

import asyncio
import json
import logging
import socket
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Set

from .. import obs
from ..obs import faults

log = logging.getLogger("uptune_tpu")

__all__ = ["RequestError", "WireServer", "WireReply", "encode_reply"]

# one reusable encoder for every reply this process writes — the
# obs/journal measurement: ~25% cheaper per object than json.dumps,
# which re-resolves its options on every call
_ENC = json.JSONEncoder(separators=(",", ":"),
                        check_circular=False).encode


class RequestError(ValueError):
    """Bad request payload (reported to the client, never fatal)."""


class WireReply(dict):
    """A response dict that carries its own wire encoding.

    The encode fast path: a handler that can assemble its reply from
    preserialized fragments (the session server's ask path splices
    per-epoch cached canonical config JSON) returns one of these with
    ``wire_text`` set to the EXACT compact JSON of the dict —
    including ``"ok"`` — and the connection loop writes the text
    verbatim instead of re-encoding.  In-process consumers see a
    plain dict; the text is invisible to them.  The text/dict
    equivalence is a hard contract (tests assert
    ``json.loads(encode_reply(r)) == dict(r)``)."""

    __slots__ = ("wire_text",)


def encode_reply(resp: dict) -> str:
    """Compact JSON text of one response — the preserialized
    ``wire_text`` when the handler provided one, the cached encoder
    otherwise."""
    t = getattr(resp, "wire_text", None)
    if t is not None:
        return t
    return _ENC(resp)


def _set_id(out: dict, rid: Any) -> None:
    """Echo the client's ``id`` into a finished reply, keeping a
    preserialized ``wire_text`` consistent: the echo is spliced in
    before the closing brace, so the fast path survives pipelining."""
    out["id"] = rid
    t = getattr(out, "wire_text", None)
    if t is not None:
        out.wire_text = t[:-1] + ',"id":' + _ENC(rid) + "}"


class WireServer:
    """One wire-speaking process: construct, ``start()``, drive
    clients against ``.port``, ``stop()``.  Subclasses own the op
    table and any per-connection/service state."""

    WIRE_NAME = "ut-wire"
    HANDLE_SPAN = "serve.handle"
    _OPS: Dict[str, Callable[..., dict]] = {}

    # connection hardening (ISSUE 15 satellite) — see module docstring
    MAX_LINE = 1 << 20
    IDLE_TIMEOUT = 1800.0
    # handler-pool bound (ISSUE 17): how many requests may execute
    # concurrently across ALL connections.  The pool is where blocking
    # handler work (group commits, checkpoint fsyncs, timeline
    # appends) lands so the event loop stays pure I/O; more workers
    # than cores only adds GIL pressure on this box
    MAX_WORKERS = 8
    # sub-ops one batch frame may carry (ISSUE 20): the request side
    # is already bounded by max_line, but k tiny sub-ops can fan out
    # into k large replies — this bounds the amplification
    MAX_BATCH_OPS = 256

    def __init__(self, host: str, port: int):
        self.host = str(host)
        self.port = int(port)
        self.max_line = int(self.MAX_LINE)
        self.idle_timeout: Optional[float] = self.IDLE_TIMEOUT
        self.max_workers = int(self.MAX_WORKERS)
        self.max_batch_ops = int(self.MAX_BATCH_OPS)
        self._lock = threading.RLock()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._running = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._stop_ev: Optional[asyncio.Event] = None
        self._tasks: Set[asyncio.Task] = set()   # loop-thread only
        self.started_unix = time.time()

    # -- per-connection hooks ------------------------------------------
    def _conn_opened(self, conn: socket.socket, addr) -> Any:
        """Called when a connection is accepted; the return value is
        this connection's state, threaded through `_on_response` and
        `_conn_closed` (None by default — stateless services skip all
        three hooks)."""
        return None

    def _on_response(self, state: Any, req: dict, resp: dict) -> None:
        """Called after every successfully parsed request is handled
        (bad-JSON lines never reach it).  Runs on the worker pool,
        directly after the handler, so response-ordering per
        connection is preserved."""

    def _conn_closed(self, state: Any) -> None:
        """Called exactly once when the connection dies — the reaping
        seam: release whatever `state` tracked.  Must never raise."""

    def _listen_banner(self) -> str:
        """Extra text for the listening log line (cosmetic)."""
        return ""

    # -- dispatch ------------------------------------------------------
    def handle(self, req: Any) -> dict:
        """Transport-free dispatch: one request dict -> one response
        dict (never raises; errors come back as ok=False).

        An optional ``ctx`` object (``{"span": id}``) is the client's
        trace context: the handler span records it as ``parent``, so
        a merged client+server trace joins each ``client.request``
        span to the ``serve.handle`` span it paid for — wire time is
        the difference (docs/OBSERVABILITY.md).

        A ``batch`` frame is unpacked here, in the kernel, so every
        subclass inherits multi-op frames with no op-table change."""
        if isinstance(req, dict) and req.get("op") == "batch":
            out = self._handle_batch(req)
            rid = req.get("id")
            if rid is not None:
                _set_id(out, rid)
            return out
        return self._handle_one(req)

    def _handle_batch(self, req: dict) -> dict:
        """One multi-op frame: dispatch each sub-op through the SAME
        per-op error wall a lone request gets, collect the ordered
        reply list, and preserialize the frame by splicing the
        sub-reply texts — sub-ops with cached wire text (the ask fast
        path) are never re-encoded.  Never raises."""
        ops = req.get("ops")
        if not isinstance(ops, list) or not ops:
            return {"ok": False,
                    "error": "batch needs 'ops': a non-empty list of "
                             "request objects"}
        if len(ops) > self.max_batch_ops:
            return {"ok": False,
                    "error": f"batch carries {len(ops)} ops; this "
                             f"server caps frames at "
                             f"{self.max_batch_ops}"}
        ctx = req.get("ctx")
        replies: List[dict] = []
        texts: List[str] = []
        failed = 0
        for sub in ops:
            if not isinstance(sub, dict):
                r: dict = {"ok": False,
                           "error": "batch sub-op must be a JSON "
                                    "object"}
            elif sub.get("op") == "batch":
                r = {"ok": False, "error": "batch frames do not nest"}
            else:
                if ctx is not None and "ctx" not in sub:
                    # the frame's trace context covers sub-ops that
                    # carry none of their own, so server spans still
                    # join the client.request span the frame paid for
                    sub = dict(sub, ctx=ctx)
                r = self._handle_one(sub)
            if not r.get("ok"):
                failed += 1
            replies.append(r)
            texts.append(encode_reply(r))
        obs.count("wire.batch_frames")
        obs.count("wire.batch_ops", len(replies))
        out = WireReply(ok=True, n=len(replies), failed=failed,
                        replies=replies)
        out.wire_text = ('{"ok":true,"n":%d,"failed":%d,"replies":[%s]}'
                         % (len(replies), failed, ",".join(texts)))
        return out

    def _handle_one(self, req: Any) -> dict:
        """Dispatch one (non-batch) request — the per-op error wall."""
        if not isinstance(req, dict):
            return {"ok": False, "error": "request must be a JSON "
                                          "object"}
        rid = req.get("id")
        op = req.get("op")
        ctx = req.get("ctx")
        # an unhashable op (list/dict) must hit the unknown-op reply,
        # not TypeError out of the dict lookup before the error wall
        fn = self._OPS.get(op) if isinstance(op, str) else None
        if fn is None:
            out = {"ok": False,
                   "error": f"unknown op {op!r}; valid: "
                            f"{sorted(self._OPS)}"}
        else:
            attrs = {"op": op}
            if isinstance(ctx, dict) and ctx.get("span") is not None:
                attrs["parent"] = str(ctx["span"])[:64]
            with obs.span(self.HANDLE_SPAN, **attrs) as sp:
                try:
                    res = fn(self, req)
                    # a WireReply already carries "ok" (and its
                    # preserialized text) — merging it into a fresh
                    # dict would throw the fast path away
                    out = (res if type(res) is WireReply
                           else {"ok": True, **res})
                except RequestError as e:
                    out = {"ok": False, "error": str(e)}
                    sp.set(error=True)
                except Exception as e:   # defensive: a client must not
                    # be able to take the serving loop down
                    log.exception("[%s] %s failed", self.WIRE_NAME, op)
                    out = {"ok": False,
                           "error": f"internal: {type(e).__name__}: {e}"}
                    sp.set(error=True)
        if rid is not None:
            _set_id(out, rid)
        return out

    def _dispatch(self, state: Any, req: dict) -> dict:
        """One request's worker-pool job: handler + response hook
        (the hook runs here, not on the loop, so a hook that blocks —
        the hub's durable timeline append — costs a worker slot, not
        the whole connection plane).  A batch frame fans the hook out
        per sub-op: connection-scoped state (the session server's
        ownership tracking keys on each sub-op's ``op``) must observe
        every sub-request, never the opaque frame."""
        resp = self.handle(req)
        if (isinstance(req, dict) and req.get("op") == "batch"
                and resp.get("ok")):
            for sub, r in zip(req.get("ops") or (),
                              resp.get("replies") or ()):
                if isinstance(sub, dict):
                    self._on_response(state, sub, r)
        else:
            self._on_response(state, req, resp)
        return resp

    # -- TCP -----------------------------------------------------------
    def start(self) -> "WireServer":
        """Bind + listen, then run the event loop in a daemon thread;
        .port holds the bound port (useful with port=0)."""
        # a serving process trades a little throughput for tail
        # latency: the interpreter's default 5ms GIL switch interval
        # parks every waiting request behind CPU-bound peers (config
        # decode, JSON, a tenant thread's own measurement loop) in
        # 5ms quanta — milliseconds of queueing on a sub-ms op.
        # BENCH_SERVE's ask p95 is measured under this setting
        if sys.getswitchinterval() > 0.001:
            sys.setswitchinterval(0.0005)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(128)
        # the socket is listening BEFORE start() returns: a client may
        # connect immediately (it queues in the backlog until the loop
        # thread starts accepting), exactly like the threaded kernel
        self.port = s.getsockname()[1]
        self._listener = s
        self._running = True
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_workers,
            thread_name_prefix=f"{self.WIRE_NAME}-worker")
        self._loop = asyncio.new_event_loop()
        # created HERE (not in the loop thread) so a stop() racing a
        # just-started server always has an event to set
        self._stop_ev = asyncio.Event()
        t = threading.Thread(target=self._run_loop,
                             name=f"{self.WIRE_NAME}-loop",
                             daemon=True)
        t.start()
        with self._lock:
            self._threads.append(t)
        log.info("[%s] listening on %s:%d%s", self.WIRE_NAME,
                 self.host, self.port, self._listen_banner())
        return self

    def _run_loop(self) -> None:
        """The event-loop thread: owns every socket until stop()."""
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._main())
        except Exception:       # defensive: the loop dying must be
            # loud, never a silent half-dead server
            log.exception("[%s] event loop failed", self.WIRE_NAME)
        finally:
            self._loop.close()

    async def _main(self) -> None:
        server = await asyncio.start_server(
            self._serve_conn, sock=self._listener,
            limit=self.max_line + 1)
        try:
            await self._stop_ev.wait()
        finally:
            server.close()
            await server.wait_closed()
            # cancelling a conn task unwinds it through its finally:
            # writer closed, registry pruned, _conn_closed ran — the
            # gather is the barrier stop() joins through
            for task in list(self._tasks):
                task.cancel()
            if self._tasks:
                await asyncio.gather(*self._tasks,
                                     return_exceptions=True)
            # let the transports' scheduled close callbacks run so
            # every conn fd is really closed before the loop exits
            await asyncio.sleep(0)
            await asyncio.sleep(0)

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        conn = writer.get_extra_info("socket")
        addr = writer.get_extra_info("peername") or ("?", 0)
        task = asyncio.current_task()
        self._tasks.add(task)
        # both registries mutate under _lock everywhere, so stop()'s
        # shutdown snapshot is never a torn read; the finally below
        # prunes this conn's entry, keeping a long-lived server's
        # registry bounded by LIVE connections under open/close churn
        with self._lock:
            self._conns.append(conn)
        state = self._conn_opened(conn, addr)
        loop = asyncio.get_running_loop()
        try:
            faults.fire("wire.accept")
            if conn is not None:
                conn.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            while self._running:
                try:
                    line = await asyncio.wait_for(
                        reader.readline(),
                        timeout=self.idle_timeout or None)
                except asyncio.TimeoutError:
                    obs.count("wire.idle_timeouts")
                    log.info("[%s] closing idle connection %s",
                             self.WIRE_NAME, addr)
                    break
                except ValueError:
                    # the stream reader's limit tripped mid-line: one
                    # complete error reply, then close — the rest of
                    # the oversized line is unread, so the stream
                    # cannot be re-synchronized
                    obs.count("wire.line_cap")
                    writer.write(_ENC(
                        {"ok": False,
                         "error": f"request line exceeds "
                                  f"{self.max_line} bytes"}
                    ).encode() + b"\n")
                    await writer.drain()
                    break
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                faults.fire("wire.read")
                try:
                    req = json.loads(line)
                except json.JSONDecodeError as e:
                    resp = {"ok": False, "error": f"bad JSON: {e}"}
                else:
                    # handler work runs on the bounded pool; awaiting
                    # it keeps THIS connection's replies in request
                    # order while every other connection's coroutine
                    # stays runnable
                    resp = await loop.run_in_executor(
                        self._pool, self._dispatch, state, req)
                faults.fire("wire.reply")
                # one coalesced send per request — for a batch frame
                # this is the spliced sub-reply texts in one line, and
                # a WireReply's preserialized text goes out verbatim
                writer.write(encode_reply(resp).encode() + b"\n")
                await writer.drain()
        except (OSError, ValueError):
            pass            # client went away mid-exchange
        except asyncio.CancelledError:
            pass            # stop(): unwind through the finally
        finally:
            self._tasks.discard(task)
            try:
                writer.close()
            except OSError:
                pass
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass    # stop() already swept it
            # the reaping hook runs on the loop thread: it must stay
            # cheap (the subclass contract) and calling it here — not
            # on the pool — guarantees exactly-once even when stop()
            # has already torn the pool down
            self._conn_closed(state)

    def stop(self) -> None:
        self._running = False
        loop = self._loop
        if loop is not None and not loop.is_closed() \
                and self._stop_ev is not None:
            try:
                loop.call_soon_threadsafe(self._stop_ev.set)
            except RuntimeError:
                pass        # loop already closed under us
        elif self._listener is not None:
            # never started (or the loop died before serving): just
            # release the port
            try:
                self._listener.close()
            except OSError:
                pass
        # bounded join: the loop thread exits once _main's finally
        # has closed the listener and every connection — joining makes
        # stop() a real barrier, so no conn coroutine races
        # interpreter teardown writing to closed sockets
        with self._lock:
            threads = list(self._threads)
        me = threading.current_thread()
        for t in threads:
            if t is not me:     # a handler op may itself call stop()
                t.join(timeout=2.0)
        if self._pool is not None:
            # wait=False: a wedged handler gets the same 2s grace the
            # threaded kernel gave, not a veto over shutdown (stop()
            # may itself be running ON a pool thread — a handler op
            # calling stop() must not join itself)
            self._pool.shutdown(wait=False, cancel_futures=True)

    def serve_forever(self) -> None:
        """start() + block until KeyboardInterrupt (the CLI path)."""
        self.start()
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            log.info("[%s] shutting down", self.WIRE_NAME)
        finally:
            self.stop()

    def __enter__(self) -> "WireServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
