"""The newline-JSON/TCP service kernel: one reusable server loop for
every wire-speaking plane in the repo.

PR 8's session server, the fleet-telemetry hub (obs/hub.py, ISSUE 14),
and the planes ROADMAP items 1-2 specify against this seam (the
sharded front tier, a remote ResultStore server) all speak the same
protocol: one JSON object per line, each carrying an ``op`` field,
answered by one JSON object per line.  This module owns the generic
half so each service only writes its op table:

* **Dispatch** — a class-level ``_OPS`` table maps op names to
  handler methods; ``handle(request) -> response`` is transport-free
  (tests and in-process benches drive it directly) and never raises:
  a ``RequestError`` comes back as ``ok=False`` with the message, any
  other exception is caught by the defensive per-op error wall and
  reported as ``internal:`` — one misbehaving client can never take
  the serving loop down.  An optional ``id`` field is echoed verbatim
  so clients may pipeline; an optional ``ctx`` span id is recorded as
  the handler span's ``parent`` so `ut-trace merge` joins
  client/server shards (docs/OBSERVABILITY.md).
* **Connection lifecycle** — thread-per-connection reader/writer
  loops around ``handle()``, with per-connection state hooks
  (``_conn_opened`` / ``_on_response`` / ``_conn_closed``) so a
  service can scope resources to the connection that created them
  and reap them when it dies — the session server's crashed-tenant
  slot reaping and the hub's source liveness both ride this seam.
* **Reaping and shutdown** — dead connections prune themselves from
  the registry (long-lived servers stay bounded by LIVE connections
  under churn); ``stop()`` closes the listener and every tracked
  connection under the lock.

Subclass contract::

    class MyServer(WireServer):
        WIRE_NAME = "my-server"          # log prefix + thread names
        def _op_ping(self, req): return {"t": time.time()}
        _OPS = {"ping": _op_ping}

``HANDLE_SPAN`` stays ``serve.handle`` for every service: the trace
merge tool joins ``client.request`` spans against that name, and a
hub or store server is as much a serving plane as the session server.
"""
from __future__ import annotations

import json
import logging
import socket
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import obs
from ..obs import faults

log = logging.getLogger("uptune_tpu")

__all__ = ["RequestError", "WireServer"]


class RequestError(ValueError):
    """Bad request payload (reported to the client, never fatal)."""


class WireServer:
    """One wire-speaking process: construct, ``start()``, drive
    clients against ``.port``, ``stop()``.  Subclasses own the op
    table and any per-connection/service state."""

    WIRE_NAME = "ut-wire"
    HANDLE_SPAN = "serve.handle"
    _OPS: Dict[str, Callable[..., dict]] = {}

    # connection hardening (ISSUE 15 satellite).  MAX_LINE caps one
    # request line: a client streaming an unterminated megarequest
    # gets one error reply and a close instead of growing a buffer
    # forever.  IDLE_TIMEOUT bounds how long a silent connection may
    # pin its reader thread (a client that connects and sends nothing
    # used to hold it until server stop); generous by default because
    # serve tenants legitimately idle across external builds —
    # instances may override either before start()
    MAX_LINE = 1 << 20
    IDLE_TIMEOUT = 1800.0

    def __init__(self, host: str, port: int):
        self.host = str(host)
        self.port = int(port)
        self.max_line = int(self.MAX_LINE)
        self.idle_timeout: Optional[float] = self.IDLE_TIMEOUT
        self._lock = threading.RLock()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._running = False
        self.started_unix = time.time()

    # -- per-connection hooks ------------------------------------------
    def _conn_opened(self, conn: socket.socket, addr) -> Any:
        """Called when a connection is accepted; the return value is
        this connection's state, threaded through `_on_response` and
        `_conn_closed` (None by default — stateless services skip all
        three hooks)."""
        return None

    def _on_response(self, state: Any, req: dict, resp: dict) -> None:
        """Called after every successfully parsed request is handled
        (bad-JSON lines never reach it)."""

    def _conn_closed(self, state: Any) -> None:
        """Called exactly once when the connection dies — the reaping
        seam: release whatever `state` tracked.  Must never raise."""

    def _listen_banner(self) -> str:
        """Extra text for the listening log line (cosmetic)."""
        return ""

    # -- dispatch ------------------------------------------------------
    def handle(self, req: Any) -> dict:
        """Transport-free dispatch: one request dict -> one response
        dict (never raises; errors come back as ok=False).

        An optional ``ctx`` object (``{"span": id}``) is the client's
        trace context: the handler span records it as ``parent``, so
        a merged client+server trace joins each ``client.request``
        span to the ``serve.handle`` span it paid for — wire time is
        the difference (docs/OBSERVABILITY.md)."""
        if not isinstance(req, dict):
            return {"ok": False, "error": "request must be a JSON "
                                          "object"}
        rid = req.get("id")
        op = req.get("op")
        ctx = req.get("ctx")
        # an unhashable op (list/dict) must hit the unknown-op reply,
        # not TypeError out of the dict lookup before the error wall
        fn = self._OPS.get(op) if isinstance(op, str) else None
        if fn is None:
            out = {"ok": False,
                   "error": f"unknown op {op!r}; valid: "
                            f"{sorted(self._OPS)}"}
        else:
            attrs = {"op": op}
            if isinstance(ctx, dict) and ctx.get("span") is not None:
                attrs["parent"] = str(ctx["span"])[:64]
            with obs.span(self.HANDLE_SPAN, **attrs) as sp:
                try:
                    out = {"ok": True, **fn(self, req)}
                except RequestError as e:
                    out = {"ok": False, "error": str(e)}
                    sp.set(error=True)
                except Exception as e:   # defensive: a client must not
                    # be able to take the serving loop down
                    log.exception("[%s] %s failed", self.WIRE_NAME, op)
                    out = {"ok": False,
                           "error": f"internal: {type(e).__name__}: {e}"}
                    sp.set(error=True)
        if rid is not None:
            out["id"] = rid
        return out

    # -- TCP -----------------------------------------------------------
    def start(self) -> "WireServer":
        """Bind + listen + accept loop in a daemon thread; .port holds
        the bound port (useful with port=0)."""
        # a serving process trades a little throughput for tail
        # latency: the interpreter's default 5ms GIL switch interval
        # parks every waiting request behind CPU-bound peers (config
        # decode, JSON, a tenant thread's own measurement loop) in
        # 5ms quanta — milliseconds of queueing on a sub-ms op.
        # BENCH_SERVE's ask p95 is measured under this setting
        if sys.getswitchinterval() > 0.001:
            sys.setswitchinterval(0.0005)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(128)
        self.port = s.getsockname()[1]
        self._listener = s
        self._running = True
        t = threading.Thread(target=self._accept_loop,
                             name=f"{self.WIRE_NAME}-accept",
                             daemon=True)
        t.start()
        with self._lock:
            self._threads.append(t)
        log.info("[%s] listening on %s:%d%s", self.WIRE_NAME,
                 self.host, self.port, self._listen_banner())
        return self

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, addr = self._listener.accept()
            except OSError:
                return      # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            faults.fire("wire.accept")
            t = threading.Thread(target=self._serve_conn,
                                 args=(conn, addr),
                                 name=f"{self.WIRE_NAME}-{addr[1]}",
                                 daemon=True)
            # both registries mutate under _lock everywhere, so
            # stop()'s shutdown snapshot is never a torn read;
            # _serve_conn prunes its own entries on exit, keeping a
            # long-lived server's registries bounded by LIVE
            # connections under open/close churn
            with self._lock:
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        if self.idle_timeout:
            # bounded reads: a stalled/silent client times out of its
            # reader thread instead of pinning it until server stop
            # (the conn closes on timeout — mid-line resync is not
            # possible on a byte stream)
            conn.settimeout(float(self.idle_timeout))
        f = conn.makefile("rwb")
        state = self._conn_opened(conn, addr)
        try:
            while True:
                try:
                    line = f.readline(self.max_line + 1)
                except (TimeoutError, socket.timeout):
                    obs.count("wire.idle_timeouts")
                    log.info("[%s] closing idle connection %s",
                             self.WIRE_NAME, addr)
                    break
                if not line:
                    break
                if len(line) > self.max_line:
                    # one complete error reply, then close: the rest
                    # of the oversized line is unread, so the stream
                    # cannot be re-synchronized
                    obs.count("wire.line_cap")
                    f.write(json.dumps(
                        {"ok": False,
                         "error": f"request line exceeds "
                                  f"{self.max_line} bytes"},
                        separators=(",", ":")).encode() + b"\n")
                    f.flush()
                    break
                line = line.strip()
                if not line:
                    continue
                faults.fire("wire.read")
                try:
                    req = json.loads(line)
                except json.JSONDecodeError as e:
                    resp = {"ok": False, "error": f"bad JSON: {e}"}
                else:
                    resp = self.handle(req)
                    self._on_response(state, req, resp)
                faults.fire("wire.reply")
                f.write(json.dumps(resp, separators=(",", ":"))
                        .encode() + b"\n")
                f.flush()
        except (OSError, ValueError):
            pass            # client went away mid-write
        finally:
            try:
                f.close()
                conn.close()
            except OSError:
                pass
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass    # stop() already swept it
                me = threading.current_thread()
                if me in self._threads:
                    self._threads.remove(me)
            self._conn_closed(state)

    def stop(self) -> None:
        self._running = False
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        # snapshot under _lock: handler threads may still be mutating
        # the registry (an accept racing the _running flip) while
        # shutdown walks it
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            # shutdown BEFORE close: the reader thread's makefile
            # object holds a reference, so close() alone only drops a
            # refcount — the fd (and the connection's claim on the
            # port) would survive until the blocked readline noticed,
            # which on an idle connection is the idle timeout away.
            # shutdown unblocks the read immediately, so a stopped
            # server really releases its port (the restart-in-place
            # path recovery depends on)
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # bounded join: handler threads unblock the moment their conn
        # is shut down above, and the accept thread exits on the
        # closed listener — joining makes stop() a real barrier, so no
        # handler races interpreter teardown writing to closed sockets
        with self._lock:
            threads = list(self._threads)
        me = threading.current_thread()
        for t in threads:
            if t is not me:     # a handler op may itself call stop()
                t.join(timeout=2.0)

    def serve_forever(self) -> None:
        """start() + block until KeyboardInterrupt (the CLI path)."""
        self.start()
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            log.info("[%s] shutting down", self.WIRE_NAME)
        finally:
            self.stop()

    def __enter__(self) -> "WireServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
