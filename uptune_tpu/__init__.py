"""uptune-tpu: a TPU-native distributed auto-tuning framework.

A ground-up JAX/XLA re-design of the capabilities of Hecmay/uptune
(reference at /root/reference): mixed discrete/continuous/permutation search
spaces, an ensemble of search techniques under an AUC multi-armed bandit,
surrogate-model pruning, and distributed black-box evaluation — with the
entire proposal side (population state, mutation/crossover operators,
surrogate fit, acquisition scoring, dedup) living on TPU as batched kernels
over flat device arrays.

The full user-facing facade (`ut.tune`, `ut.target`, `ut.config`, ...) is
assembled lazily in `uptune_tpu.api`; the core layers are importable
directly:

    from uptune_tpu.space import Space, FloatParam
    from uptune_tpu import techniques, driver
"""
__version__ = "0.1.0"

_LAZY = {
    # public name -> (module, attribute)
    "tune": ("uptune_tpu.api.tuneapi", "tune"),
    "target": ("uptune_tpu.api.report", "target"),
    "interm": ("uptune_tpu.api.report", "interm"),
    "feature": ("uptune_tpu.api.report", "feature"),
    "save": ("uptune_tpu.api.report", "save"),
    "get_global_id": ("uptune_tpu.api.report", "get_global_id"),
    "get_local_id": ("uptune_tpu.api.report", "get_local_id"),
    "get_meta_data": ("uptune_tpu.api.report", "get_meta_data"),
    "config": ("uptune_tpu.api.session", "config"),
    "init": ("uptune_tpu.api.session", "init"),
    "get_best": ("uptune_tpu.api.session", "get_best"),
    "rule": ("uptune_tpu.api.constraint", "rule"),
    "constraint": ("uptune_tpu.api.constraint", "constraint"),
    "register": ("uptune_tpu.api.constraint", "register"),
    "vars": ("uptune_tpu.api.constraint", "vars"),
    "model": ("uptune_tpu.api.tuner", "model"),
    "settings": ("uptune_tpu.api.session", "settings"),
    # batched multi-instance engine (engine/batched.py): N on-device
    # tunes of one space as ONE compiled vmapped program
    "tune_batch": ("uptune_tpu.api.batch", "tune_batch"),
    # tuning-as-a-service (serve/, docs/SERVING.md): client for the
    # `ut serve` multi-tenant session server, and the offline sibling
    "connect": ("uptune_tpu.serve.client", "connect"),
    "LocalSession": ("uptune_tpu.serve.session", "LocalSession"),
    # EDA report extractors (reference report.py:122-174)
    "vhls": ("uptune_tpu.api.features", "vhls"),
    "quartus": ("uptune_tpu.api.features", "quartus"),
    # QuickEst estimator pipeline (reference __init__.py:10-43 maps
    # preprocess/train/test from uptune.quickest)
    "preprocess": ("uptune_tpu.quickest", "preprocess"),
    "train": ("uptune_tpu.quickest", "train"),
    "test": ("uptune_tpu.quickest", "test"),
    "predict": ("uptune_tpu.quickest", "predict"),
    # QuickEst analysis + HLS-report extraction (reference
    # quickest/analyze.py:498, quickest/extract/LegUp/funcs.py:270-447)
    "analyze": ("uptune_tpu.quickest", "analyze"),
    "extract": ("uptune_tpu.quickest", "extract"),
}


def __getattr__(name):
    """Lazy public API, the equivalent of the reference's custom lazy module
    (`/root/reference/python/uptune/__init__.py:71-143`) without replacing
    the module object."""
    try:
        modname, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module 'uptune_tpu' has no attribute {name!r}")
    import importlib
    try:
        return getattr(importlib.import_module(modname), attr)
    except ModuleNotFoundError as e:
        raise AttributeError(
            f"uptune_tpu.{name} is declared but its module {modname} is not "
            f"available yet: {e}") from e


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
