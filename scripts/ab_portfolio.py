"""Matched A/B: AUCBanditMetaTechniqueTPU (CMA-ES-carrying portfolio)
vs AUCBanditMetaTechniqueA (reference-faithful default), same seeds,
same budget, same problem — VERDICT r3 weak #3: the registration
comment in techniques/bandit.py compared a 10-seed CMA median against a
30-seed portfolio-A median; this script produces the symmetric 30-seed
evidence (and updates that comment's claim if it flips).

    python scripts/ab_portfolio.py --seeds 30 \
        --state ab_state.jsonl --out AB_PORTFOLIO.md

Protocol (mirrors scripts/benchreport.py's rosenbrock-4d row): 4-D
rosenbrock, solved = QoR <= 1.0, budget 4000 evals, no surrogate;
iterations-to-threshold with censored runs recorded at the budget.
Per-run rows checkpoint to --state so a crashed sweep resumes.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import cpuenv  # noqa: F401  (hang-proof platform; must precede jax)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

THRESH = 1.0
BUDGET = 4000
PORTFOLIOS = ("AUCBanditMetaTechniqueA", "AUCBanditMetaTechniqueTPU")


def one_run(technique: str, seed: int) -> dict:
    from uptune_tpu.driver.driver import Tuner
    from uptune_tpu.workloads import rosenbrock_objective, rosenbrock_space

    space = rosenbrock_space(4, -2.048, 2.048)
    t = Tuner(space, rosenbrock_objective(4), seed=seed,
              technique=technique)
    res = t.run(test_limit=BUDGET, target=THRESH)
    t.close()
    it = next((i + 1 for i, v in enumerate(res.trace) if v <= THRESH),
              BUDGET)
    return {"technique": technique, "seed": seed, "iters": it,
            "best": res.best_qor,
            "censored": it >= BUDGET and res.best_qor > THRESH}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=30)
    ap.add_argument("--state", default="ab_state.jsonl")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    done = {}
    if os.path.exists(args.state):
        with open(args.state) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                done[(r["technique"], r["seed"])] = r
    state_f = open(args.state, "a")

    rows = {p: [] for p in PORTFOLIOS}
    for s in range(args.seeds):
        for p in PORTFOLIOS:
            key = (p, 1000 + s)
            r = done.get(key)
            if r is None:
                r = one_run(p, 1000 + s)
                state_f.write(json.dumps(r) + "\n")
                state_f.flush()
            rows[p].append(r)
            print(f"  {p} seed={1000 + s} iters={r['iters']} "
                  f"censored={r['censored']}", file=sys.stderr)

    lines = [
        "# A/B: CMA-ES portfolio vs portfolio A "
        f"({args.seeds} matched seeds)",
        "",
        "rosenbrock-4d, solved = QoR <= 1.0, budget 4000, no surrogate;",
        "identical seed list per arm.  Censored runs count at the",
        "budget (flattering the arm that censors more — read the",
        "solve-rate with the median).",
        "",
        "| portfolio | median iters | IQR | solved |",
        "|---|---|---|---|",
    ]
    med = {}
    for p in PORTFOLIOS:
        iters = [r["iters"] for r in rows[p]]
        cens = sum(r["censored"] for r in rows[p])
        med[p] = float(np.median(iters))
        lines.append(
            f"| {p} | {med[p]:.0f} "
            f"| {np.percentile(iters, 25):.0f}-"
            f"{np.percentile(iters, 75):.0f} "
            f"| {args.seeds - cens}/{args.seeds} |")
    a, b = PORTFOLIOS
    lines += ["", f"ratio (TPU/A): **{med[b] / med[a]:.2f}**", ""]
    text = "\n".join(lines)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
