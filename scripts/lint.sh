#!/usr/bin/env bash
# Pre-commit gate: ut-lint over uptune_tpu/, failing on NEW findings.
#
# Grandfathered findings (if any) live in scripts/lint_baseline.json;
# the tree is currently clean, so no baseline file exists.  If a rule
# lands that flags legacy code you cannot fix in the same change,
# refresh the baseline once:
#
#   python -m uptune_tpu.analysis uptune_tpu/ bench.py scripts/ \
#       --write-baseline scripts/lint_baseline.json
#
# (the path set must match the gate invocation below, or findings
# outside uptune_tpu/ can never be grandfathered)
#
# and fix the grandfathered findings down over time.  Intentional
# hazards get a per-line '# ut-lint: disable=R00X' with a rationale
# comment instead (docs/LINT.md).
set -euo pipefail
cd "$(dirname "$0")/.."

args=(uptune_tpu/ bench.py scripts/ --format text)
if [ -f scripts/lint_baseline.json ]; then
    args+=(--baseline scripts/lint_baseline.json)
fi
# UT_LINT_CHANGED=1: diff-scoped pre-commit loop — lint only files
# changed vs UT_LINT_BASE (default HEAD) plus untracked ones.  The
# suppression-free sweep below still runs package-wide, so the
# cross-module rules (R101) keep their full view
if [ "${UT_LINT_CHANGED:-0}" = "1" ]; then
    args+=(--changed --changed-base "${UT_LINT_BASE:-HEAD}")
fi
"${PYTHON:-python3}" -m uptune_tpu.analysis "${args[@]}"

# uptune_tpu/store/, uptune_tpu/surrogate/, uptune_tpu/engine/,
# uptune_tpu/ops/, uptune_tpu/obs/ and uptune_tpu/serve/ must stay
# SUPPRESSION-FREE on top of clean: cache-correctness code (what
# decides whether a build is skipped, ISSUE 4; since ISSUE 18 the
# package also carries the cooperative search fabric — store/server.py
# whose ack-after-durable append IS the zero-acked-loss contract, and
# store/remote.py whose write-behind flusher sits on the tell path of
# every cooperating tuner), the concurrent
# background-refit plane (ISSUE 5), the fused/batched engine + Pallas
# kernels every perf headline rests on (ISSUE 6; since ISSUE 19
# ops/acquire.py fuses surrogate score + acquisition + top-k into
# the single device program the propose path and BENCH_MULTI's
# fused-vs-unfused A/B are measured through, routed by
# ops/routing.py's UT_PALLAS knob), the observability
# plane whose instrumentation lives INSIDE every hot path (ISSUE 7 —
# a silenced hazard there would tax or skew the very measurements it
# exists to make; the ISSUE 10 distributed-obs modules — sidecar,
# flight, merge, top — the ISSUE 12 search-quality modules —
# journal, quality, report — the ISSUE 13 device-telemetry
# module — device.py, which wraps EVERY engine/driver device program
# — and the ISSUE 14 fleet-telemetry modules — ship.py, whose
# offer() sits on the driver/serve hot paths, and hub.py, the
# collector every process reports into, and the ISSUE 15
# fault-injection registry obs/faults.py, whose fire() sits
# permanently inside the wire/checkpoint/store/pool seams — are
# part of the obs/ package and inherit the rule), and the
# multi-tenant serving plane (ISSUE 8 — a silenced retrace or
# host-sync hazard there stalls EVERY tenant at once; since
# ISSUE 14 serve/wire.py is the service kernel EVERY wire-speaking
# plane runs on — rebuilt in ISSUE 17 as a single asyncio event
# loop whose handlers run on a bounded worker pool, so a lock held
# across a blocking call now stalls the whole connection plane, not
# one thread — since ISSUE 15 serve/durable.py is the write-ahead
# checkpoint plane the zero-committed-loss contract rests on,
# since ISSUE 17 serve/router.py is the sharded front tier whose
# supervisor thread + session map sit in front of every shard, and
# since ISSUE 20 the batched wire plane threads the whole package:
# serve/wire.py owns the batch-frame dispatch + WireReply encode
# fast path every reply rides, serve/session.py applies whole
# tell_many batches inside ONE group-lock hold (a hazard there now
# stalls k tells, not one), and serve/server.py + serve/client.py
# splice preserialized reply fragments whose text/dict equivalence
# is a correctness contract) get
# no '# ut-lint: disable' escape hatch and no baseline
"${PYTHON:-python3}" - <<'EOF'
import json, subprocess, sys
rc = 0
for pkg in ("uptune_tpu/store", "uptune_tpu/surrogate",
            "uptune_tpu/engine", "uptune_tpu/ops", "uptune_tpu/obs",
            "uptune_tpu/serve"):
    r = subprocess.run(
        [sys.executable, "-m", "uptune_tpu.analysis", pkg,
         "--format", "json", "--show-suppressed"],
        capture_output=True, text=True)
    doc = json.loads(r.stdout)
    if doc["findings"]:
        print(f"ut-lint: {pkg}/ must be finding- AND "
              f"suppression-free:", file=sys.stderr)
        for f in doc["findings"]:
            print(f"  {f['path']}:{f['line']} {f['rule']} "
                  f"(suppressed={f.get('suppressed', False)})",
                  file=sys.stderr)
        rc = 1
sys.exit(rc)
EOF
