"""Scaling-headroom bench: the fused engine at larger arm scales.

The headline `BENCH_TPU.json` (bench.py, arm scale 64, ~6k candidates
per step) measured 1.239M acq/s on one TPU v5 lite chip with HBM
utilization ~0.001 — the pipeline at that size is latency-bound, so
throughput should rise substantially with batch until bandwidth or the
dedup sort saturates.  This script walks a scale ladder and writes the
evidence to BENCH_TPU_SCALED.json (separate artifact — the headline's
fixed sizing stays comparable across rounds).

Each ladder step runs in a KILLABLE SUBPROCESS: the axon tunnel can
wedge mid-compile, and larger programs compile for minutes, so a hang
at one scale must not lose the measurements already taken.

Usage: python scripts/bench_scaled.py  (prints one JSON line per step,
then a summary; exits nonzero if nothing landed on tpu)
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_STEP_CODE = """
import json, time, sys
import jax
from uptune_tpu.engine import FusedEngine, default_arms
from uptune_tpu.workloads import rosenbrock_device, rosenbrock_space
scale, cap_bits, steps = (int(sys.argv[1]), int(sys.argv[2]),
                          int(sys.argv[3]))
space = rosenbrock_space(16, -5.0, 5.0)
eng = FusedEngine(space, lambda v, p: rosenbrock_device(v),
                  arms=default_arms(scale=scale),
                  history_capacity=1 << cap_bits)
state = eng.init(jax.random.PRNGKey(0))
t0 = time.perf_counter()
run = jax.jit(lambda s: eng.run(s, steps)).lower(state).compile()
compile_s = time.perf_counter() - t0
state = run(state)
jax.block_until_ready(state)
reps = []
for _ in range(3):
    s = eng.init(jax.random.PRNGKey(1))
    jax.block_until_ready(s)
    t0 = time.perf_counter()
    s = run(s)
    jax.block_until_ready(s)
    reps.append(time.perf_counter() - t0)
best = min(reps)
print("UT_ROW=" + json.dumps({
    "scale": scale, "history_capacity_bits": cap_bits, "steps": steps,
    "batch_per_step": eng.total_batch, "compile_s": round(compile_s, 1),
    "rep_wall_s": [round(t, 4) for t in reps],
    "rate": round(steps * eng.total_batch / best, 1),
    "platform": jax.devices()[0].platform,
    "device_kind": getattr(jax.devices()[0], "device_kind", "?")}))
"""

LADDER = [(64, 15, 200),   # the headline sizing, as the anchor
          (128, 16, 100),
          (256, 17, 100),
          # r5: two more rungs — with the merge-based history insert
          # (driver/history.py) the per-step sort no longer grows with
          # capacity, so the ladder should keep climbing while the
          # program is latency-bound (~5 ms/step at 6k batch).  Fewer
          # steps per rung keeps compile+run inside the 900 s kill.
          (512, 17, 50),
          (1024, 18, 50)]


def main() -> None:
    rows = []
    for scale, cap, steps in LADDER:
        try:
            out = subprocess.run(
                [sys.executable, "-c", _STEP_CODE, str(scale), str(cap),
                 str(steps)], capture_output=True, text=True,
                timeout=900, cwd=REPO)
        except subprocess.TimeoutExpired:
            print(f"bench_scaled: scale {scale} hung >900s — skipped",
                  file=sys.stderr)
            continue
        row = None
        for line in out.stdout.splitlines():
            if line.startswith("UT_ROW="):
                row = json.loads(line[len("UT_ROW="):])
        if row is None:
            print(f"bench_scaled: scale {scale} failed rc="
                  f"{out.returncode}: {out.stderr.strip()[-300:]}",
                  file=sys.stderr)
            continue
        rows.append(row)
        print(json.dumps(row))
    tpu_rows = [r for r in rows if r["platform"] not in ("cpu",)]
    if not tpu_rows:
        print("bench_scaled: no step landed on an accelerator",
              file=sys.stderr)
        sys.exit(1)
    artifact = {
        "metric": "candidate_acquisitions_per_sec_per_chip_scaled",
        "unit": "configs/s",
        "platform": tpu_rows[0]["platform"],
        "device_kind": tpu_rows[0]["device_kind"],
        "best_rate": max(r["rate"] for r in tpu_rows),
        "captured_unix": time.time(),
        "ladder": rows,
        "note": ("scaling-headroom evidence; the cross-round headline "
                 "is the fixed-size BENCH_TPU.json"),
    }
    with open(os.path.join(REPO, "BENCH_TPU_SCALED.json"), "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({"best_rate": artifact["best_rate"],
                      "platform": artifact["platform"]}))


if __name__ == "__main__":
    main()
