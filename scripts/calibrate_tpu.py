"""Calibration grid for the surrogate-mode settings in benchreport.

Runs a handful of seeds per (problem, variant) and prints median
iters-to-threshold, so SURROGATE_SOPTS choices are evidence-backed rather than
guessed.  Variants are small dict overrides on top of SURROGATE_SOPTS.

Usage: python scripts/calibrate_tpu.py [--seeds 6] [--problems ...]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import cpuenv  # noqa: F401  (hang-proof platform)

import numpy as np

from benchreport import PROBLEMS, SURROGATE_SOPTS, one_run

VARIANTS = {
    "old": {"propose_batch": 0, "min_points": 32, "refit_interval": 32,
            "score": "lcb"},
    "new": {},
    "pb16": {"propose_batch": 16},
    "every3": {"propose_every": 3},
    "lcb-pool": {"score": "lcb"},
    "minp32": {"min_points": 32, "refit_interval": 32},
    "pool128": {"pool_mult": 128},
    "minp8": {"min_points": 8, "refit_interval": 8},
    "kf50": {"keep_frac": 0.5},
    "kf25": {"keep_frac": 0.25},
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=6)
    ap.add_argument("--problems", nargs="*",
                    default=["rosenbrock-2d", "rosenbrock-4d",
                             "gcc-options"])
    ap.add_argument("--variants", nargs="*", default=list(VARIANTS))
    ap.add_argument("--state", default="calib_state.jsonl")
    args = ap.parse_args()

    done = {}
    if os.path.exists(args.state):
        with open(args.state) as f:
            for line in f:
                try:
                    r = json.loads(line)
                    done[(r["problem"], r["variant"], r["seed"])] = r
                except json.JSONDecodeError:
                    pass
    sf = open(args.state, "a")
    for prob in args.problems:
        budget = PROBLEMS[prob]()[3]
        for var in args.variants:
            # cached rows are only valid for the SAME effective settings
            # and budget (same staleness class benchreport._sopts_sig
            # guards against)
            sig = json.dumps({**SURROGATE_SOPTS, **VARIANTS[var],
                              "budget": budget}, sort_keys=True)
            iters = []
            for s in range(args.seeds):
                key = (prob, var, 1000 + s)
                if key in done and done[key].get("sig") == sig:
                    iters.append(done[key]["iters"])
                    continue
                t0 = time.time()
                r = one_run(prob, "surrogate", seed=1000 + s, budget=budget,
                            sopts_override=VARIANTS[var])
                import jax
                jax.clear_caches()
                iters.append(r["iters"])
                sf.write(json.dumps({"problem": prob, "variant": var,
                                     "seed": 1000 + s, "sig": sig,
                                     **r}) + "\n")
                sf.flush()
                print(f"  {prob} {var} seed={s} iters={r['iters']}"
                      f"{' (censored)' if r['censored'] else ''} "
                      f"[{time.time() - t0:.0f}s]", file=sys.stderr)
            print(json.dumps({
                "problem": prob, "variant": var,
                "median": float(np.median(iters)),
                "iqr": [float(np.percentile(iters, 25)),
                        float(np.percentile(iters, 75))]}))


if __name__ == "__main__":
    main()
