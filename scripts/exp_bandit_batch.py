"""Experiment: does pull-size parity fix the bandit-arbitrated plane's
endgame starvation?

The r4 10-seed sweep showed arbitration='bandit' censoring rosenbrock-4d
seeds that the scheduled plane solves (0/30 censored, median 346).
Mechanism hypothesis: the plane's 8-eval pool tickets inflate its AUC
use_count 4x faster per evaluation than the techniques' ~32-eval
batches; once new bests get rare near the optimum, the exploration term
sqrt(2*log2(|history|)/use_count) dominates every score and the
most-pulled arm — the plane — ranks last exactly where its local
refinement is the move that finishes the run.

Arms: propose_batch in {8 (sweep config), 16, 32} under bandit
arbitration, 10 seeds, rosenbrock-4d protocol (thresh 1.0, budget
4000).  Usage: python scripts/exp_bandit_batch.py [--seeds N]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import cpuenv  # noqa: F401,E402  platform guard before jax

import numpy as np  # noqa: E402

from benchreport import one_run  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=10)
    ap.add_argument("--batches", type=int, nargs="*", default=[16, 32])
    ap.add_argument("--state", default="exp_bandit_batch.jsonl")
    args = ap.parse_args()

    done = {}
    if os.path.exists(args.state):
        with open(args.state) as f:
            for line in f:
                r = json.loads(line)
                done[(r["batch"], r["seed"])] = r
    with open(args.state, "a") as out:
        for batch in args.batches:
            rows = []
            for s in range(args.seeds):
                key = (batch, 1000 + s)
                if key in done:
                    rows.append(done[key])
                    continue
                r = one_run("rosenbrock-4d", "surrogate-bandit",
                            seed=1000 + s, budget=4000,
                            sopts_override={"propose_batch": batch})
                r.update({"batch": batch, "seed": 1000 + s})
                rows.append(r)
                out.write(json.dumps(r) + "\n")
                out.flush()
                import jax
                jax.clear_caches()
                print(f"  batch={batch} seed={s} iters={r['iters']}"
                      f"{' (censored)' if r['censored'] else ''}",
                      file=sys.stderr)
            iters = np.asarray([r["iters"] for r in rows])
            print(json.dumps({
                "batch": batch, "seeds": args.seeds,
                "median_iters": float(np.median(iters)),
                "iqr": [float(np.percentile(iters, 25)),
                        float(np.percentile(iters, 75))],
                "censored": int(sum(r["censored"] for r in rows))}))


if __name__ == "__main__":
    main()
