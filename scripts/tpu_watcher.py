"""Round-long TPU capture watcher (VERDICT r3 next-step #1).

The axon tunnel on this box wedges transiently (BENCH_r01..r03 never saw
`platform:"tpu"`; the r3 judge reproduced the hang themselves).  A
once-per-round 240 s probe keeps losing the lottery, so this watcher runs
for the WHOLE round: it probes the accelerator in killable subprocesses
every few minutes and, the moment the backend initializes, runs the full
(non-quick) `bench.py`, which writes the BENCH_TPU.json evidence artifact
(per-rep wall times, device repr, XLA flops/bytes, roofline util).

Every attempt is logged with a timestamp — to stdout AND to the log
file the script itself writes under exp_archives/ (run artifacts live
there, not at the repo root — ISSUE 7 hygiene; override with
UT_WATCHER_LOG) — so if the tunnel never opens all round the on-disk
log is the proof without any shell redirection.

Usage:  nohup python scripts/tpu_watcher.py >/dev/null 2>&1 &
        tail -f exp_archives/tpu_watcher.log
"""
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOTAL_BUDGET_S = float(os.environ.get("UT_WATCHER_BUDGET_S", 11.0 * 3600))
PROBE_TIMEOUT_S = 120.0
SLEEP_S = 180.0

LOG_PATH = os.environ.get(
    "UT_WATCHER_LOG", os.path.join(REPO, "exp_archives",
                                   "tpu_watcher.log"))

PROBE_CODE = ("import jax; d = jax.devices()[0]; "
              "print('UT_PLATFORM=' + d.platform)")

_log_f = None


def log(msg: str) -> None:
    global _log_f
    line = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {msg}"
    print(line, flush=True)
    if _log_f is None:
        os.makedirs(os.path.dirname(LOG_PATH), exist_ok=True)
        _log_f = open(LOG_PATH, "a", buffering=1)
    _log_f.write(line + "\n")


def probe() -> str:
    """One killable probe; returns platform name ('' if no accelerator)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_CODE], capture_output=True,
            text=True, timeout=PROBE_TIMEOUT_S, cwd=REPO)
    except subprocess.TimeoutExpired:
        return "HUNG"
    for line in out.stdout.splitlines():
        if line.startswith("UT_PLATFORM="):
            return line.split("=", 1)[1].strip()
    return f"rc={out.returncode}:{out.stderr.strip()[-200:]}"


def main() -> None:
    deadline = time.monotonic() + TOTAL_BUDGET_S
    attempt = 0
    log(f"watcher start: budget {TOTAL_BUDGET_S/3600:.1f}h, "
        f"probe timeout {PROBE_TIMEOUT_S:.0f}s, interval {SLEEP_S:.0f}s")
    while time.monotonic() < deadline:
        attempt += 1
        t0 = time.monotonic()
        plat = probe()
        dt = time.monotonic() - t0
        if plat and plat not in ("cpu", "HUNG") and not plat.startswith("rc="):
            have_std = os.path.exists(os.path.join(REPO, "BENCH_TPU.json"))
            if not have_std:
                log(f"attempt {attempt}: accelerator UP ({plat}, "
                    f"{dt:.1f}s) — running full bench")
                env = dict(os.environ, UT_BENCH_PROBE_BUDGET_S="600")
                args = [sys.executable, os.path.join(REPO, "bench.py")]
                want, done_msg = ('"platform": "tpu"',
                                  "BENCH_TPU.json captured — watcher done")
            else:
                # standard artifact already banked this round: hunt the
                # SCALED measurement instead (scale ladder, separate
                # BENCH_TPU_SCALED.json — never overwrites the headline)
                log(f"attempt {attempt}: accelerator UP ({plat}, "
                    f"{dt:.1f}s) — standard artifact exists; running "
                    f"scaled bench")
                env = dict(os.environ)
                args = [sys.executable,
                        os.path.join(REPO, "scripts", "bench_scaled.py")]
                want, done_msg = ('"platform": "tpu"',
                                  "BENCH_TPU_SCALED.json captured — "
                                  "watcher done")
            try:
                r = subprocess.run(args, capture_output=True, text=True,
                                   timeout=3600, cwd=REPO, env=env)
            except subprocess.TimeoutExpired:
                # the tunnel can wedge MID-RUN too; surviving that is
                # this watcher's whole job — log and keep watching
                log("bench hung >3600s (tunnel wedged mid-run?) — "
                    "killed; continuing to watch")
                time.sleep(SLEEP_S)
                continue
            log(f"bench rc={r.returncode}")
            log(f"bench stdout: {r.stdout.strip()}")
            log(f"bench stderr tail: {r.stderr.strip()[-800:]}")
            if r.returncode == 0 and want in r.stdout:
                log(done_msg)
                return
            log("bench did not land on tpu (tunnel closed mid-run?); "
                "continuing to watch")
        else:
            log(f"attempt {attempt}: no accelerator ({plat}, {dt:.1f}s)")
        time.sleep(max(0.0, min(SLEEP_S, deadline - time.monotonic())))
    log(f"watcher exhausted {TOTAL_BUDGET_S/3600:.1f}h budget after "
        f"{attempt} attempts without a TPU — tunnel never opened this round")


if __name__ == "__main__":
    main()
