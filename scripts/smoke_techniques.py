import sys, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).parent))
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
import cpuenv  # noqa: F401
import jax
import jax.numpy as jnp

from uptune_tpu.space import params as P
from uptune_tpu.space.spec import Space
from uptune_tpu.techniques import base as tb
from uptune_tpu.techniques.bandit import MetaTechnique

space = Space([P.FloatParam('x', -5, 5), P.FloatParam('y', -5, 5),
               P.IntParam('n', 0, 10), P.EnumParam('e', options=('a', 'b', 'c')),
               P.PermParam('p', items=tuple(range(8)))])


def rosen_eval(cands):
    u = space.decode_scalars(cands.u)
    x, y = u[:, 0], u[:, 1]
    return (1 - x) ** 2 + 100 * (y - x * x) ** 2


names = tb.all_technique_names()
print(len(names), 'techniques')
key = jax.random.PRNGKey(0)
for nm in names:
    t = tb.get_technique(nm)
    if isinstance(t, MetaTechnique):
        continue
    if not t.supports(space):
        print('skip', nm)
        continue
    k1, k2, key = jax.random.split(key, 3)
    st = t.init_state(space, k1)
    best = tb.Best.empty(space)
    for i in range(3):
        kk = jax.random.fold_in(k2, i)
        st, cands = t.propose(space, st, kk, best)
        assert cands.u.shape[0] == t.natural_batch(space), (nm, cands.u.shape)
        qor = rosen_eval(cands)
        best = best.update(cands, qor)
        st = t.observe(space, st, cands, qor, best)
    print('ok', nm, float(best.qor))
