"""Surrogate ranking-quality parity benchmark.

The reference prunes proposals with an XGBoost regressor ensemble
(300 trees, depth 10, lr 0.015, 94-feature vectors —
/root/reference/python/uptune/plugins/xgbregressor.py:35-44,55).  The
multivoting filter only works if the surrogate RANKS candidates well, so
the bar for the JAX GP/MLP replacement is ranking parity with a strong
tree oracle on EDA-shaped data (SURVEY §7.5).

xgboost is not in this environment; the oracle is sklearn's
GradientBoostingRegressor with the reference's exact hyperparameters —
the same algorithm family (gradient-boosted depth-10 trees).

Usage:  python scripts/surrogate_bench.py [--n 600] [--feat 94]
Prints one JSON line per model: spearman + precision@10% on a held-out
split of a synthetic 94-feature EDA-like response surface.
"""
import argparse
import json
import os
import sys
import time

import numpy as np


def make_eda_dataset(seed: int, n: int, n_feat: int = 94,
                     noise: float = 0.05, fn_seed: int = 1234):
    """Synthetic post-synthesis-QoR-like surface over [0,1]^F: sparse
    linear trend + threshold (resource cliff) effects + pairwise
    interactions + many irrelevant features + mild heteroscedastic
    noise — the qualitative structure of EDA report features.

    The response FUNCTION is drawn from `fn_seed` (fixed across
    train/test splits); `seed` draws only the sample points."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, n_feat).astype(np.float32)
    fn_rng = np.random.RandomState(fn_seed)
    w = np.zeros(n_feat, np.float32)
    active = fn_rng.choice(n_feat, 20, replace=False)
    w[active] = fn_rng.randn(20).astype(np.float32)
    y = x @ w
    y += 2.0 * np.sin(3 * np.pi * x[:, 0]) * x[:, 1]
    y += 3.0 * (x[:, 2] > 0.7) * x[:, 3]          # resource cliff
    y += 2.0 * x[:, 4] * x[:, 5]
    y += 1.5 * (x[:, 6] - 0.5) ** 2
    y += noise * (1.0 + x[:, 7]) * rng.randn(n).astype(np.float32)
    return x, y.astype(np.float32)


def spearman(a: np.ndarray, b: np.ndarray) -> float:
    ra = np.argsort(np.argsort(a)).astype(np.float64)
    rb = np.argsort(np.argsort(b)).astype(np.float64)
    ra -= ra.mean()
    rb -= rb.mean()
    return float((ra * rb).sum() /
                 np.sqrt((ra ** 2).sum() * (rb ** 2).sum()))


def precision_at(a_true: np.ndarray, a_pred: np.ndarray,
                 frac: float = 0.1) -> float:
    """Fraction of the predicted-best `frac` that are truly best-`frac`
    (minimization: smaller is better)."""
    k = max(1, int(len(a_true) * frac))
    top_true = set(np.argsort(a_true)[:k].tolist())
    top_pred = set(np.argsort(a_pred)[:k].tolist())
    return len(top_true & top_pred) / k


def run(n: int = 600, n_feat: int = 94, n_test: int = 300,
        seed: int = 0, quick: bool = False):
    xtr, ytr = make_eda_dataset(seed, n, n_feat)
    xte, yte = make_eda_dataset(seed + 1, n_test, n_feat)
    out = {}

    # tree oracle (reference hyperparameters, xgbregressor.py:35-44)
    from sklearn.ensemble import GradientBoostingRegressor
    t0 = time.time()
    gbr = GradientBoostingRegressor(
        n_estimators=50 if quick else 300, max_depth=10,
        learning_rate=0.1 if quick else 0.015, random_state=seed)
    gbr.fit(xtr, ytr)
    pred = gbr.predict(xte)
    out["oracle_gbt"] = {
        "spearman": spearman(yte, pred),
        "p_at_10": precision_at(yte, pred),
        "fit_s": round(time.time() - t0, 2),
    }

    import jax
    import jax.numpy as jnp
    from uptune_tpu.surrogate import gp, mlp

    # R005 suppressions below: each jax.jit(f)(x) wrapper in this
    # one-shot report script runs exactly once per process, so there is
    # no cache to miss — and fit_s deliberately INCLUDES compile time
    # (that is the cost a user pays on first fit)
    t0 = time.time()
    state = jax.jit(gp.fit_auto)(                 # ut-lint: disable=R005
        jnp.asarray(xtr), jnp.asarray(ytr))
    mu, _ = jax.jit(gp.predict)(state, jnp.asarray(xte))  # ut-lint: disable=R005
    out["gp_mll"] = {
        "spearman": spearman(yte, np.asarray(mu)),
        "p_at_10": precision_at(yte, np.asarray(mu)),
        "fit_s": round(time.time() - t0, 2),
        "lengthscale": round(float(state.lengthscale), 4),
        "noise": float(state.noise),
    }

    t0 = time.time()
    state_f = jax.jit(lambda x, y: gp.fit(x, y))(  # ut-lint: disable=R005
        jnp.asarray(xtr), jnp.asarray(ytr))
    mu_f, _ = jax.jit(gp.predict)(state_f, jnp.asarray(xte))  # ut-lint: disable=R005
    out["gp_fixed"] = {
        "spearman": spearman(yte, np.asarray(mu_f)),
        "p_at_10": precision_at(yte, np.asarray(mu_f)),
        "fit_s": round(time.time() - t0, 2),
    }

    t0 = time.time()
    ms = jax.jit(lambda k, x, y: mlp.fit(k, x, y))(  # ut-lint: disable=R005
        jax.random.PRNGKey(seed), jnp.asarray(xtr), jnp.asarray(ytr))
    mmu, _ = jax.jit(mlp.predict)(ms, jnp.asarray(xte))  # ut-lint: disable=R005
    out["mlp_ens"] = {
        "spearman": spearman(yte, np.asarray(mmu)),
        "p_at_10": precision_at(yte, np.asarray(mmu)),
        "fit_s": round(time.time() - t0, 2),
    }
    return out


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import cpuenv  # noqa: F401  (hang-proof platform for standalone runs)
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=600)
    ap.add_argument("--feat", type=int, default=94)
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    for name, metrics in run(n=args.n, n_feat=args.feat,
                             quick=args.quick).items():
        print(json.dumps({"model": name, **metrics}))
