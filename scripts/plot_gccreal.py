"""Render the gcc-real convergence evidence figure from the committed
per-run traces (benchreport_state_r4.jsonl = baseline arm,
benchreport_state_r4c.jsonl = surrogate arm under the shipping
run-budget rule; 10 matched seeds each, protocol v2).

One axis: median-across-seeds best-so-far, normalized to each run's own
-O2 anchor (so runs measured against slightly different anchors are
comparable), vs evaluation index.  Carry-forward past a run's end —
best-so-far is still defined after a run stops.  Colors are the
dataviz reference palette's categorical slots 1-2 in fixed order
(validated pair); the threshold is a neutral gray reference line, not
a series.

    python scripts/plot_gccreal.py          # -> docs/img/gccreal_r4.png
"""
import json
import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARMS = [
    ("baseline (seeded bandit)", "benchreport_state_r4.jsonl",
     "baseline", "#2a78d6"),
    ("surrogate (shipping config)", "benchreport_state_r4c.jsonl",
     "surrogate", "#eb6834"),
]
BUDGET = 80
THRESH_FRAC = 0.78


def median_curve(path: str, mode: str) -> np.ndarray:
    rows = [json.loads(l) for l in open(os.path.join(HERE, path))]
    rows = [r for r in rows
            if r.get("problem") == "gcc-real" and r.get("mode") == mode
            and "trace" in r]
    curves = []
    for r in rows:
        t_o2 = r["thresh"] / THRESH_FRAC
        tr = [v / t_o2 for v in r["trace"] if v is not None]
        best = np.minimum.accumulate(np.asarray(tr, float))
        # carry the final best-so-far to the budget edge
        pad = np.full(max(0, BUDGET - len(best)),
                      best[-1] if len(best) else np.nan)
        curves.append(np.concatenate([best[:BUDGET], pad]))
    return np.median(np.stack(curves), axis=0)


def main() -> None:
    fig, ax = plt.subplots(figsize=(7.2, 4.2))
    for label, path, mode, color in ARMS:
        med = median_curve(path, mode)
        x = np.arange(1, len(med) + 1)
        # no end-of-line direct labels: the two arms converge to the
        # same value, so the legend alone carries identity cleanly
        ax.plot(x, med, color=color, linewidth=2, label=label)
    ax.axhline(THRESH_FRAC, color="#9a9a9a", linewidth=1,
               linestyle=(0, (4, 3)))
    ax.annotate("solved: 22% under -O2", (BUDGET, THRESH_FRAC),
                textcoords="offset points", xytext=(-4, 5), ha="right",
                fontsize=8, color="#777777")
    ax.set_xlabel("evaluations (real g++ compiles)")
    ax.set_ylabel("median best wall time / -O2 anchor")
    ax.set_title("gcc-real (qsort): best-so-far across 10 matched "
                 "seeds, protocol v2", fontsize=10)
    ax.set_xlim(1, BUDGET)
    ax.grid(True, color="#e6e6e6", linewidth=0.6)
    ax.set_axisbelow(True)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    ax.legend(frameon=False, fontsize=8, loc="upper right")
    out = os.path.join(HERE, "docs", "img", "gccreal_r4.png")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    fig.tight_layout()
    fig.savefig(out, dpi=160)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
