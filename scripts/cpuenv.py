"""Shared preamble for ad-hoc CPU-only scripts (mirrors tests/conftest.py):
force the virtual 8-device CPU platform and drop the axon TPU-tunnel
backend factory before any JAX backend initializes."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)
