"""Shared preamble for ad-hoc CPU-only scripts (same guard as
tests/conftest.py): force the virtual 8-device CPU platform and drop the
axon TPU-tunnel backend factory before any JAX backend initializes."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from uptune_tpu.utils.platform_guard import force_cpu  # noqa: E402

force_cpu(8)
