"""Experiment: cross-payload feature screening on REAL g++ tuning
(r4 verdict next-step #3).

The r4 diagnosis: on gcc-real (80 evals, ~330 params -> ~1,100 one-hot
lanes) the GP stays prior-dominated; the best measured arm (bandit
arbitration, 8-eval pulls) reached 0.88x baseline.  The attacks here:
TRANSFER — per-flag sensitivity mined from full-budget archives of the
OTHER payloads over the same mined space (surrogate/screen.py), as a
hard top-k restriction or a soft per-lane ARD reweighting — and the
transfer-free ONLINE flip bias (per-flag |corr| over the run's own
observations steering the pool's flip moves).  All three measured
negative-to-neutral on qsort; see BENCHREPORT.md "Cross-payload
screening on gcc-real (r5)".

Phases (each resumable via its jsonl state):
  archives — full-80-eval baseline runs per payload, trials recorded to
             exp_archives/gccreal_<payload>_<seed>.jsonl
  run      — the screened surrogate-bandit arm on a target payload,
             screen built from the OTHER payloads' archives
  online   — the transfer-free online flip-bias arm

Every arm runs the benchreport gcc-real protocol (same seeds 1000+,
seeded -O2 trial, 0.78x-anchor threshold, budget 80) through the shared
_run_arm loop, so arms stay protocol-identical by construction.

Usage:
  python scripts/exp_screen_gccreal.py archives [--payloads qsort,mmm,stencil]
  python scripts/exp_screen_gccreal.py run --target qsort [--seeds 30]
      [--top 16,24] [--soft] [--flip-only]
  python scripts/exp_screen_gccreal.py online --target qsort [--seeds 10]

MUST run on an otherwise idle box: the objective is measured binary
runtime.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import cpuenv  # noqa: F401,E402  platform guard before jax

import numpy as np  # noqa: E402

from benchreport import PROBLEMS, one_run  # noqa: E402

PAYLOADS = ("qsort", "mmm", "stencil")
ARCH_DIR = "exp_archives"
ARCH_SEEDS = (2000, 2001, 2002)


def _prob_name(payload: str) -> str:
    return "gcc-real" if payload == "qsort" else f"gcc-real-{payload}"


def _arch_path(payload: str, seed: int) -> str:
    return os.path.join(ARCH_DIR, f"gccreal_{payload}_{seed}.jsonl")


def gen_archives(payloads) -> None:
    os.makedirs(ARCH_DIR, exist_ok=True)
    for payload in payloads:
        for seed in ARCH_SEEDS:
            path = _arch_path(payload, seed)
            if os.path.exists(path) and os.path.getsize(path):
                print(f"  {path}: exists, skipping", file=sys.stderr)
                continue
            r = one_run(_prob_name(payload), "baseline", seed=seed,
                        budget=80, archive=path, stop_at_target=False)
            print(f"  {payload} seed={seed} rows->{path} "
                  f"best={r['best']:.4f}", file=sys.stderr)
            import jax
            jax.clear_caches()


def _run_arm(target: str, arm: str, seeds: int, state_path: str,
             sopts: dict, summary: str) -> None:
    """Shared arm loop: resume from the jsonl state, run the missing
    seeds under the benchreport gcc-real protocol (mode
    'surrogate-bandit', budget 80, seeds 1000+), append rows, print the
    summary.  Every arm routes through here so the arms stay
    protocol-identical by construction."""
    prob = _prob_name(target)
    done = {}
    if os.path.exists(state_path):
        with open(state_path) as f:
            for line in f:
                r = json.loads(line)
                done[(r["target"], r["arm"], r["seed"])] = r
    rows = []
    with open(state_path, "a") as out:
        for s in range(seeds):
            seed = 1000 + s
            key = (target, arm, seed)
            if key in done:
                rows.append(done[key])
                continue
            r = one_run(prob, "surrogate-bandit", seed=seed, budget=80,
                        sopts_override=dict(sopts))
            r.update({"target": target, "arm": arm, "seed": seed})
            rows.append(r)
            out.write(json.dumps(r) + "\n")
            out.flush()
            import jax
            jax.clear_caches()
            print(f"  {target} {arm} seed={s} iters={r['iters']}"
                  f"{' (censored)' if r['censored'] else ''}",
                  file=sys.stderr)
    iters = np.asarray([r["iters"] for r in rows])
    print(json.dumps({
        "arm": f"{target} {arm} ({summary})",
        "seeds": len(rows),
        "median_iters": float(np.median(iters)),
        "iqr": [float(np.percentile(iters, 25)),
                float(np.percentile(iters, 75))],
        "censored": int(sum(r["censored"] for r in rows))}))


def run_online(target: str, seeds: int, state_path: str) -> None:
    """The online flip-bias arm: NO transfer, no screen — the plane's
    flip moves are re-weighted at each refit by per-flag |corr| over
    the run's own observations (manager flip_bias='online')."""
    _run_arm(target, "online-flip", seeds, state_path,
             {"propose_batch_parity": False, "flip_bias": "online"},
             "bandit, batch 8, online flip bias")


def run_screened(target: str, seeds: int, top: str, state_path: str,
                 flip_only: bool = False, soft: bool = False) -> None:
    from uptune_tpu.surrogate.screen import screen_from_archives

    top_cont, top_cat = (int(x) for x in top.split(","))
    prob = _prob_name(target)
    space = PROBLEMS[prob]()[0]   # also measures the anchor (cached)
    others = [p for p in PAYLOADS if p != target]
    paths = [_arch_path(p, s) for p in others for s in ARCH_SEEDS]
    sc = screen_from_archives(space, paths, top_cont=top_cont,
                              top_cat=top_cat)
    if sc is None:
        print("no archives found — run the 'archives' phase first",
              file=sys.stderr)
        sys.exit(1)
    n_src = sum(1 for p in paths if os.path.exists(p))
    arm = f"screen-{top}" + ("-fliponly" if flip_only else "") \
        + ("-soft" if soft else "")
    print(f"screen for {target}: {n_src} source archives from "
          f"{others}, kept {sc.n_cont} cont lanes + {sc.n_cat} groups "
          f"({len(sc.idx)} of {space.n_surrogate_features} lanes)",
          file=sys.stderr)
    if flip_only:
        # ablation: keep the full-width GP, only bias the flip moves
        sc = sc._replace(idx=np.arange(space.n_surrogate_features,
                                       dtype=np.int32),
                         n_cont=space.n_cont_features,
                         n_cat=space.n_cat)
    sopts = {"propose_batch_parity": False, "screen": sc}
    if soft:
        sopts["screen_mode"] = "soft"
    _run_arm(target, arm, seeds, state_path, sopts,
             "bandit, batch 8, screened")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("phase", choices=("archives", "run", "online"))
    ap.add_argument("--payloads", default=",".join(PAYLOADS))
    ap.add_argument("--target", default="qsort", choices=PAYLOADS)
    ap.add_argument("--seeds", type=int, default=10)
    ap.add_argument("--top", default="16,24")
    ap.add_argument("--flip-only", action="store_true",
                    help="ablation: full-width GP, screened flip bias")
    ap.add_argument("--soft", action="store_true",
                    help="soft ARD mode: full width, per-lane "
                         "sensitivity scaling instead of restriction")
    ap.add_argument("--state", default="exp_screen_gccreal.jsonl")
    args = ap.parse_args()
    if args.phase == "archives":
        gen_archives([p for p in args.payloads.split(",") if p])
    elif args.phase == "online":
        run_online(args.target, args.seeds, args.state)
    else:
        run_screened(args.target, args.seeds, args.top, args.state,
                     flip_only=args.flip_only, soft=args.soft)


if __name__ == "__main__":
    main()
