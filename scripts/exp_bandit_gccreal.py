"""Experiment: can AUC credit limit in-run surrogate damage on gcc-real
WITHOUT the static run-budget rule?

The r3/r4 four-arm analysis (BENCHREPORT "Why the surrogate does not
beat the bandit on gcc-real") measured forced-on in-loop guidance at 29
median iters vs the seeded bandit's 19.5 — the plane's pool tickets
displace scarce bandit batches on an 80-eval budget.  The shipping
default passivates the plane there (run-budget rule, ratio 0.92).

This arm measures the third option: arbitration='bandit' with
auto_passive disabled and pull-size parity OFF (8-eval pulls are the
affordable size on an 80-eval budget; parity would make each pull ~40%
of the budget).  If the AUC credit works as designed, the bandit tries
the plane once or twice after it fits (~16 evals in), sees no new
bests, and starves it — landing between the seeded bandit (19.5) and
forced-on (29), much closer to the former.

Protocol matches benchreport gcc-real v2 exactly (same seeds, seeded
declared-defaults trial, 22%-under-anchor threshold, budget 80); rows
append to exp_bandit_gccreal.jsonl.  MUST run on an otherwise idle box:
the objective is measured binary runtime.

Usage: python scripts/exp_bandit_gccreal.py [--seeds N]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import cpuenv  # noqa: F401,E402  platform guard before jax

import numpy as np  # noqa: E402

from benchreport import one_run  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=10)
    ap.add_argument("--state", default="exp_bandit_gccreal.jsonl")
    args = ap.parse_args()

    done = {}
    if os.path.exists(args.state):
        with open(args.state) as f:
            for line in f:
                r = json.loads(line)
                done[r["seed"]] = r
    rows = []
    with open(args.state, "a") as out:
        for s in range(args.seeds):
            seed = 1000 + s
            if seed in done:
                rows.append(done[seed])
                continue
            r = one_run("gcc-real", "surrogate-bandit", seed=seed,
                        budget=80,
                        sopts_override={"propose_batch_parity": False})
            r["seed"] = seed
            rows.append(r)
            out.write(json.dumps(r) + "\n")
            out.flush()
            import jax
            jax.clear_caches()
            print(f"  seed={s} iters={r['iters']}"
                  f"{' (censored)' if r['censored'] else ''}",
                  file=sys.stderr)
    iters = np.asarray([r["iters"] for r in rows])
    print(json.dumps({
        "arm": "gcc-real surrogate-bandit (no budget rule, batch 8)",
        "seeds": len(rows),
        "median_iters": float(np.median(iters)),
        "iqr": [float(np.percentile(iters, 25)),
                float(np.percentile(iters, 75))],
        "censored": int(sum(r["censored"] for r in rows))}))


if __name__ == "__main__":
    main()
