"""Iterations-to-optimum benchmark: the north-star denominator.

BASELINE.md's second target — "iterations-to-optimum <= 50% of the
reference baseline on rosenbrock + gcc-options" — had no measured
denominator (the reference publishes no numbers; its own protocol is a
per-technique 30-run sweep, /root/reference/samples/rosenbrock/
Makefile:1-30).  This harness measures both sides with our
implementation of the reference's algorithms:

* baseline mode — the reference's search stack faithfully: the default
  AUC-bandit portfolio (same techniques, same credit math), no
  surrogate filtering.  Iteration = one black-box evaluation, exactly
  the reference's unit (one config per desired_result() call,
  opentuner/search/driver.py:160-207).
* surrogate mode — the same portfolio plus the surrogate plane: GP
  surrogate with marginal-likelihood hyperparameter fitting, EI top-k
  batch concentration (only the predicted-best half of each proposed
  batch is evaluated), and the surrogate PROPOSAL plane — every other
  acquisition the manager emits its own EI-maximizing batch from an
  oversampled pool (uniform + multi-scale incumbent perturbations),
  scored on device where ranking thousands of candidates is free.
  (This mode was called "tpu" through round 2; renamed because it names
  an ALGORITHM stack, not the platform it ran on — legacy "tpu" rows in
  state/rows files are read as "surrogate".)

Metric per run: number of EVALUATIONS until best-so-far reaches the
space's optimum threshold (censored at the eval budget).  Reported:
median over seeds, per space and mode, plus the surrogate/baseline
ratio.

Spaces:
* rosenbrock-2d / -4d — the reference's own framework-test fixture
  (samples/rosenbrock/rosenbrock.py:1-60).
* gcc-options-shaped — ~200 mixed params mined the way the reference
  mines gcc (samples/gcc-options/tune_gcc.py:127-128: -O level, on/off
  optimizer flags, numeric --param values) over a deterministic
  synthetic runtime model with a known optimum.

Usage: python scripts/benchreport.py [--seeds 30] [--quick] [--out md]
"""
import argparse
import json
import math
import os
import sys
import time

import numpy as np


# --------------------------------------------------------------- spaces
def rosenbrock_problem(dim: int = 2):
    from uptune_tpu.space.params import FloatParam
    from uptune_tpu.space.spec import Space

    space = Space([FloatParam(f"x{i}", -2.048, 2.048)
                   for i in range(dim)])

    def objective(cfgs):
        x = np.asarray([[c[f"x{i}"] for i in range(dim)] for c in cfgs])
        return (100.0 * (x[:, 1:] - x[:, :-1] ** 2) ** 2
                + (1.0 - x[:, :-1]) ** 2).sum(1)

    # optimum 0 at x=1; "solved" thresholds calibrated so the baseline
    # reaches them within budget on most seeds (0.1 censors nearly every
    # 4-D baseline run)
    if dim <= 2:
        return space, objective, 0.1, 2000
    return space, objective, 1.0, 4000


def gcc_problem(n_flags: int = 120, n_params: int = 60, n_enums: int = 19,
                fn_seed: int = 7):
    """A gcc-options-shaped space (~200 mixed params) over a synthetic
    runtime model: base time per -O level, per-flag effects (some only
    active at -O2+, mirroring real pass interactions), quadratic
    penalties for numeric --param values around hidden sweet spots, and
    a few pairwise flag interactions.  Deterministic with a known
    optimum by construction."""
    from uptune_tpu.space.params import BoolParam, EnumParam, IntParam
    from uptune_tpu.space.spec import Space

    rng = np.random.RandomState(fn_seed)
    specs = [EnumParam("olevel", ("-O0", "-O1", "-O2", "-O3"))]
    for i in range(n_flags):
        specs.append(BoolParam(f"f{i}"))
    lo = rng.randint(0, 8, n_params)
    hi = lo + rng.randint(8, 256, n_params)
    for i in range(n_params):
        specs.append(IntParam(f"p{i}", int(lo[i]), int(hi[i])))
    enum_opts = ("a", "b", "c")
    for i in range(n_enums):
        specs.append(EnumParam(f"e{i}", enum_opts))
    space = Space(specs)

    olevel_base = np.asarray([10.0, 6.0, 4.5, 4.2])
    w_flag = rng.randn(n_flags) * 0.25          # + hurts, - helps
    gated = rng.rand(n_flags) < 0.3             # only active at -O2+
    sweet = lo + (hi - lo) * rng.rand(n_params)
    w_param = rng.rand(n_params) * 0.4 / ((hi - lo) ** 2)
    pair_i = rng.choice(n_flags, 10, replace=False)
    pair_j = rng.choice(n_flags, 10, replace=False)
    w_pair = rng.randn(10) * 0.3
    w_enum = rng.randn(n_enums, len(enum_opts)) * 0.15

    def objective(cfgs):
        out = np.empty(len(cfgs))
        for r, c in enumerate(cfgs):
            ol = int(c["olevel"][2])
            flags = np.asarray([c[f"f{i}"] for i in range(n_flags)],
                               np.float64)
            act = flags * np.where(gated, float(ol >= 2), 1.0)
            pv = np.asarray([c[f"p{i}"] for i in range(n_params)],
                            np.float64)
            ev = np.asarray(
                [enum_opts.index(c[f"e{i}"]) for i in range(n_enums)])
            t = olevel_base[ol]
            t += (act * w_flag).sum()
            t += (w_param * (pv - sweet) ** 2).sum()
            t += (act[pair_i] * act[pair_j] * w_pair).sum()
            t += w_enum[np.arange(n_enums), ev].sum()
            out[r] = t
        return out

    # ACHIEVABLE optimum anchor: greedily construct the best config per
    # -O level (flags on iff their active flag-weight is negative,
    # params at the nearest integer to the sweet spot, argmin enums) and
    # EVALUATE it — an attainable QoR by construction, unlike a
    # lower bound that can overshoot what any search can reach (the
    # earlier -|w_pair| bound made every run censor).
    best = np.inf
    for ol in range(4):
        act_scale = np.where(gated, float(ol >= 2), 1.0)
        cfg = {"olevel": f"-O{ol}"}
        for i in range(n_flags):
            cfg[f"f{i}"] = bool(w_flag[i] * act_scale[i] < 0)
        for i in range(n_params):
            cfg[f"p{i}"] = int(np.clip(round(sweet[i]), lo[i], hi[i]))
        for i in range(n_enums):
            cfg[f"e{i}"] = enum_opts[int(np.argmin(w_enum[i]))]
        best = min(best, float(objective([cfg])[0]))
    # default config: -O0, all flags off, params at lo, enums 'a'
    dflt = float(objective([{**{f"f{i}": False for i in range(n_flags)},
                             **{f"p{i}": int(lo[i])
                                for i in range(n_params)},
                             **{f"e{i}": "a" for i in range(n_enums)},
                             "olevel": "-O0"}])[0])
    # threshold: capture 90% of the greedy-achievable improvement
    thresh = best + 0.10 * (dflt - best)
    return space, objective, float(thresh), 6000


_GCC_REAL_CACHE = {}


def gcc_real_problem(payload: str = "qsort", budget: int = 80):
    """REAL g++ tuning (VERDICT r2 missing #3 / weak #4): the mined
    ~330-param space of samples/gcc-options/mine_gcc.py over actual
    compiles + runs of a real payload on the installed compiler —
    'qsort' (branchy sort/search), 'mmm' (cache-blocked matmul), or
    'stencil' (SIMD-bound integer stencil).  Solved = beating the plain
    `-O2` default build's wall time by 22% (protocol v2: anchor
    measured once per process so every seed/mode chases the same bar;
    see the threshold comment below).  Evaluation is serial real work
    (~2-4s per config on this 1-core box) — run with --problems
    gcc-real[-mmm|-stencil] and a handful of seeds, not in the default
    synthetic sweep."""
    import math

    if payload in _GCC_REAL_CACHE:
        return _GCC_REAL_CACHE[payload]

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "samples", "gcc-options"))
    import mine_gcc

    mined = mine_gcc.mine()
    space = mine_gcc.build_space(mined)
    # seed config for BOTH modes (the CLI's declared-defaults seed trial,
    # exec/controller.py; the reference's first trial is likewise the
    # user's written defaults — tune_gcc.py declares "-O2"): -O2, every
    # flag untouched, every --param at the compiler's own default
    prob_name = "gcc-real" if payload == "qsort" else f"gcc-real-{payload}"
    SEED_CONFIGS[prob_name] = [{
        "olevel": "-O2",
        **{fl: "default" for fl in mined["flags"]},
        **{n: int(min(max(d, lo), hi))
           for n, (lo, hi, d) in mined["params"].items()},
    }]
    src_name = "mmm_block.cpp" if payload == "mmm" \
        else f"payload_{payload}.cpp"
    src = os.path.join(os.path.dirname(os.path.abspath(
        mine_gcc.__file__)), src_name)

    # anchor: plain -O2 defines both the time-to-beat and the reference
    # output every tuned build must reproduce (the correctness gate in
    # mine_gcc.build_and_time — without it the tuner "wins" with
    # ABI-breaking miscompiles like -fpack-struct)
    expected = mine_gcc.anchor_output(src)

    def objective(cfgs):
        return np.asarray([mine_gcc.build_and_time(
            mine_gcc.config_to_cmd(c, mined), src, expected=expected,
            compile_timeout=90, run_timeout=30) for c in cfgs])

    # anchor time = min over measurement rounds of 5 runs each, with a
    # real pause between rounds: on a 1-core box a transient background
    # burst inflates t_o2, which silently loosens the threshold for the
    # whole sweep (observed: +15% anchor -> every seed "solved" in 1-4
    # iters); the pause lets a burst that spans one round end before
    # the next
    import time as _time
    rounds = []
    for i in range(2):
        if i:
            _time.sleep(15.0)
        rounds.append(mine_gcc.build_and_time(
            ["-O2"], src, expected=expected, runs=5,
            compile_timeout=90, run_timeout=30))
    t_o2 = min(rounds)
    if not math.isfinite(t_o2):
        raise RuntimeError("gcc-real -O2 anchor build failed or did not "
                           "validate; is g++ installed?")
    # 22% under -O2: with the declared-defaults seed trial (-O2 itself)
    # now injected into every run, the old 15% bar fell inside the first
    # technique batch for baseline AND surrogate (both solved in 6 iters,
    # r4 calibration) — it stopped measuring search.  The tuned optimum
    # on this box is ~29% under -O2, so 22% is reachable but requires
    # genuine flag-space search.  Full traces are stored per run, so any
    # other threshold can be re-evaluated post-hoc without re-compiling.
    thresh = 0.78 * t_o2
    print(f"gcc-real: |space|={len(space.specs)} params, "
          f"-O2 anchor {t_o2:.4f}s, threshold {thresh:.4f}s",
          file=sys.stderr)
    _GCC_REAL_CACHE[payload] = (space, objective, float(thresh), budget)
    return _GCC_REAL_CACHE[payload]


PROBLEMS = {
    "rosenbrock-2d": lambda: rosenbrock_problem(2),
    "rosenbrock-4d": lambda: rosenbrock_problem(4),
    "gcc-options": gcc_problem,
    # real-build problems: resolvable by name but excluded from the
    # default sweep (real compiles; see gcc_real_problem docstring)
    "gcc-real": gcc_real_problem,
    "gcc-real-mmm": lambda: gcc_real_problem("mmm"),
    # SIMD-bound integer stencil (payload_stencil.cpp): -O3/vectorizer
    # flag territory, ~33% under -O2 reachable on this box (-O3
    # -funroll-loops alone), so the 0.78x bar demands real flag search
    "gcc-real-stencil": lambda: gcc_real_problem("stencil"),
}
DEFAULT_PROBLEMS = [p for p in PROBLEMS if not p.startswith("gcc-real")]

# problem -> configs injected as seed trials before run() for EVERY mode
# (populated by problem factories; empty for the synthetic spaces so
# their published 30-seed rows stay valid)
SEED_CONFIGS = {}

# Static full budgets, mirroring what each factory returns.  The --rows
# staleness merge reads budgets from HERE, never by instantiating the
# factory: gcc_real_problem() mines the real g++ space and runs two -O2
# anchor builds plus a 15 s settle — side effects a merge-only pass must
# not trigger (and that raise on a g++-less box, killing the --out write
# after the sweep already finished).  run_suite() asserts the factory's
# budget against this table, so drift is caught on every real run.
PROBLEM_BUDGETS = {
    "rosenbrock-2d": 2000,
    "rosenbrock-4d": 4000,
    "gcc-options": 6000,
    "gcc-real": 80,
    "gcc-real-mmm": 80,
    "gcc-real-stencil": 80,
}

# Measurement-protocol version per problem: bumped whenever the way a
# row is MEASURED changes (threshold definition, seeding, payload) —
# budget+sopts_sig alone cannot see such changes, so without this a
# state/rows file carrying pre-change rows would silently merge two
# protocols into one table (r4: gcc-real gained the -O2 seed trial and
# moved the threshold 0.85→0.78×t_O2).  Synthetic problems are at their
# original protocol (None == legacy rows remain valid).
# Whether the driver's run-budget rule engages at the problem's full
# budget — the ACTUAL predicate (_apply_budget_rule: test_limit <
# space.n_scalar), not a problem-name prefix (ADVICE r5: keying the
# budget_rule=v2 'surrogate' fingerprint on the 'gcc-real' name would
# silently merge pre- and post-v2 rows for any future problem entering
# the small-budget regime).  Static for the same reason as
# PROBLEM_BUDGETS (merge-only passes must not instantiate factories
# with build side effects); run_suite asserts it against the real
# space, so drift — e.g. a g++ whose mined flag count drops below the
# budget — is caught on every real run.  The budget itself is
# fingerprinted separately, so scaled (--quick) budgets never alias.
PROBLEM_SMALL_BUDGET = {
    "rosenbrock-2d": False,     # 2000 evals >> 2 scalar params
    "rosenbrock-4d": False,
    "gcc-options": False,       # 6000 evals >> mined flag count
    "gcc-real": True,           # 80 evals < ~330 mined g++ flags
    "gcc-real-mmm": True,
    "gcc-real-stencil": True,
}

PROBLEM_PROTO = {
    "gcc-real": "v2:seeded+0.78xO2",
    "gcc-real-mmm": "v2:seeded+0.78xO2",
    # +u32: the payload's arithmetic went wrap-defined unsigned (r4
    # review — int32 sums overflowed, UB, and -ftrapv configs aborted),
    # changing both the anchor digest and the feasible set; rows
    # measured against the UB-era source must not be reused
    "gcc-real-stencil": "v2:seeded+0.78xO2+u32",
}


# ---------------------------------------------------------------- runs
def iters_to_threshold(trace, thresh: float, budget: int) -> int:
    for i, v in enumerate(trace):
        if v <= thresh:
            return i + 1
    return budget  # censored


# The calibrated settings are the package-level defaults (selected by
# scripts/calibrate_tpu.py, validated at 30 seeds in BENCHREPORT.md):
# with the proposal plane carrying exploitation, arm batches prune
# harder than the filter-only era could afford (keep_frac 0.25 used to
# censor rosenbrock-4d; now 0.25-0.5 all work, 0.35 is the across-space
# compromise), EI beats LCB for top-k ranking, and the sparse-lane pool
# moves are what carry gcc-options-shaped spaces.
# (uptune_tpu.calibrated is deliberately jax-import-free: this module
# body runs before __main__ installs the cpuenv platform guard.)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from uptune_tpu.calibrated import CALIBRATED_OPTS  # noqa: E402

SURROGATE_SOPTS = dict(CALIBRATED_OPTS)

# pre-round-3 artifacts called surrogate mode "tpu"; normalize on read so
# published 30-seed rows survive the rename
_LEGACY_MODES = {"tpu": "surrogate"}


def _norm_mode(m: str) -> str:
    return _LEGACY_MODES.get(m, m)


def one_run(problem: str, mode: str, seed: int, budget: int,
            sopts_override: dict = None, archive: str = None,
            stop_at_target: bool = True):
    """`archive` records every trial to a driver jsonl (the
    cross-payload screening experiments mine these);
    `stop_at_target=False` runs the full budget even after the
    threshold is reached (more archive rows per run)."""
    from uptune_tpu.driver.driver import Tuner

    mode = _norm_mode(mode)
    space, objective, thresh, _ = PROBLEMS[problem]()
    surrogate = None
    sopts = None
    if mode == "surrogate":
        surrogate = "gp"
        sopts = dict(SURROGATE_SOPTS)
        if sopts_override:
            sopts.update(sopts_override)
    elif mode == "surrogate-bandit":
        # the same calibrated plane, acquisitions arbitrated by the AUC
        # bandit (virtual arm, driver pull-size parity) instead of the
        # fixed schedule.  auto_passive is pinned False so the mode
        # always measures the ACTIVE plane under arbitration — on the
        # synthetic problems the pin is a no-op (budgets dwarf the
        # parameter counts, the rule would never passivate), but on a
        # tiny-budget problem this arm deliberately diverges from the
        # shipped default, which would passivate there (driver
        # _apply_budget_rule applies in BOTH arbitration modes)
        surrogate = "gp"
        sopts = dict(SURROGATE_SOPTS, arbitration="bandit",
                     auto_passive=False)
        if sopts_override:
            sopts.update(sopts_override)
    tuner = Tuner(space, objective, seed=seed, surrogate=surrogate,
                  surrogate_opts=sopts, archive=archive)
    t0 = time.time()
    # seed trials (identical for every mode): library-mode analogue of
    # the CLI's declared-defaults seed (exec/controller.py seed trial)
    seed_cfgs = SEED_CONFIGS.get(problem)
    if seed_cfgs:
        for tr_ in tuner.inject(seed_cfgs, "seed"):
            tuner.tell(tr_, float(np.asarray(
                objective([tr_.config])).reshape(-1)[0]))
    res = tuner.run(test_limit=budget,
                    target=thresh if stop_at_target else None)
    wall = time.time() - t0
    tuner.close()
    it = iters_to_threshold(res.trace, thresh, budget)
    row = {"iters": it, "best": res.best_qor, "evals": res.evals,
           "wall_s": round(wall, 1),
           "censored": it >= budget and res.best_qor > thresh}
    if problem.startswith("gcc-real"):
        # real-build runs are expensive: store the full best-so-far
        # trace (and the threshold it was judged against) so any other
        # threshold can be evaluated post-hoc without re-compiling
        row["thresh"] = round(float(thresh), 6)
        row["trace"] = [None if not math.isfinite(v) else round(v, 6)
                        for v in res.trace]
    return row


def _sopts_sig(mode: str, problem: str = ""):
    """Fingerprint of the settings a cached row was measured under."""
    mode = _norm_mode(mode)
    if mode == "surrogate":
        # budget_rule=v2: the driver's small-budget rule now applies
        # the bandit-arbitrated recipe instead of passivating (r5).
        # Only problems in that regime (budget < n_scalar — the
        # driver's own predicate, mirrored statically in
        # PROBLEM_SMALL_BUDGET) had their pre-v2 "surrogate" rows
        # change meaning; the synthetic sweeps (budget >> params, rule
        # never engages) keep their cached 30-seed rows
        if PROBLEM_SMALL_BUDGET.get(problem, False):
            return json.dumps(dict(SURROGATE_SOPTS, budget_rule="v2"),
                              sort_keys=True)
        return json.dumps(SURROGATE_SOPTS, sort_keys=True)
    if mode == "surrogate-bandit":
        # propose_batch_parity is a DRIVER behavior (pool batch raised
        # to the median arm batch), recorded in the sig so pre-parity
        # rows (r4 first sweep, benchreport_state_r4d.jsonl) are never
        # merged into parity-era tables
        return json.dumps(dict(SURROGATE_SOPTS, arbitration="bandit",
                               auto_passive=False,
                               propose_batch_parity=True),
                          sort_keys=True)
    return "baseline"


def _load_state(path):
    done = {}
    if path and os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                r["mode"] = _norm_mode(r["mode"])
                done[(r["problem"], r["mode"], r["seed"])] = r
    return done


def run_suite(problems, seeds: int, budget_scale: float = 1.0,
              state_path: str = None, modes=("baseline", "surrogate")):
    """Per-run results checkpoint to `state_path` (jsonl) so a crashed
    sweep resumes instead of redoing hours of runs."""
    done = _load_state(state_path)
    state_f = open(state_path, "a") if state_path else None
    rows = []
    for prob in problems:
        prob_space, _, _, full_budget = PROBLEMS[prob]()
        assert full_budget == PROBLEM_BUDGETS[prob], (
            f"{prob}: factory budget {full_budget} != static table "
            f"{PROBLEM_BUDGETS[prob]} — update PROBLEM_BUDGETS")
        small = full_budget < prob_space.n_scalar
        assert small == PROBLEM_SMALL_BUDGET.get(prob, False), (
            f"{prob}: budget {full_budget} vs n_scalar "
            f"{prob_space.n_scalar} => small-budget rule {small}, but "
            f"PROBLEM_SMALL_BUDGET says otherwise — update the table "
            f"(its value keys the budget_rule=v2 cache fingerprint)")
        # the driver evaluates its predicate on the SCALED run budget;
        # a scale that flips the regime relative to the static table
        # would fingerprint v2 rows as non-v2 (or vice versa) and
        # alias them — refuse loudly instead of writing aliased rows
        scaled_small = int(full_budget * budget_scale) < \
            prob_space.n_scalar
        assert scaled_small == small, (
            f"{prob}: budget_scale={budget_scale} moves the run "
            f"across the small-budget boundary (scaled "
            f"{int(full_budget * budget_scale)} vs n_scalar "
            f"{prob_space.n_scalar}) — rows at this scale would alias "
            f"the budget_rule=v2 fingerprint; pick a scale on the "
            f"same side as the full budget")
        budget = int(full_budget * budget_scale)
        for mode in (_norm_mode(m) for m in modes):
            per_seed = []
            for s in range(seeds):
                key = (prob, mode, 1000 + s)
                cached = done.get(key)
                # a cached row is only valid for the SAME budget AND the
                # same tpu-mode surrogate settings — a --quick state file
                # must not leak half-budget iters into a full run's
                # table, and rows measured under older TPU_SOPTS must
                # not be reported as the current mode's numbers (legacy
                # rows without the fields are always re-run)
                sig = _sopts_sig(mode, prob)
                proto = PROBLEM_PROTO.get(prob)
                if cached is not None and \
                        cached.get("budget") == budget and \
                        cached.get("sopts_sig") == sig and \
                        cached.get("proto") == proto:
                    per_seed.append(cached)
                    continue
                r = one_run(prob, mode, seed=1000 + s, budget=budget)
                r["budget"] = budget
                r["sopts_sig"] = sig
                if proto is not None:
                    r["proto"] = proto
                per_seed.append(r)
                # every run builds a fresh Tuner => fresh jitted
                # programs; without this the executable cache grows
                # unboundedly across the sweep until LLVM OOMs
                # (observed twice at ~100 runs in)
                import jax
                jax.clear_caches()
                if state_f is not None:
                    state_f.write(json.dumps(
                        {"problem": prob, "mode": mode,
                         "seed": 1000 + s, **r}) + "\n")
                    state_f.flush()
                print(f"  {prob} {mode} seed={s} iters={r['iters']}"
                      f"{' (censored)' if r['censored'] else ''} "
                      f"best={r['best']:.4g} [{r['wall_s']}s]",
                      file=sys.stderr)
            iters = np.asarray([r["iters"] for r in per_seed])
            rows.append({
                "problem": prob, "mode": mode, "seeds": seeds,
                "budget": budget, "sopts_sig": _sopts_sig(mode, prob),
                "proto": PROBLEM_PROTO.get(prob),
                "median_iters": float(np.median(iters)),
                "iqr": [float(np.percentile(iters, 25)),
                        float(np.percentile(iters, 75))],
                "censored": int(sum(r["censored"] for r in per_seed)),
            })
            print(json.dumps(rows[-1]))
    return rows


def to_markdown(rows, seeds):
    # per-row seed counts are authoritative (merged rows may have been
    # measured at a different count than this invocation's --seeds)
    counts = sorted({r["seeds"] for r in rows}) or [seeds]
    seeds_txt = "/".join(str(c) for c in counts)
    lines = [
        "# BENCHREPORT — iterations-to-optimum",
        "",
        "Median evaluations until best-so-far reaches the space's",
        "optimum threshold (rosenbrock-2d: QoR <= 0.1; -4d: <= 1.0;",
        "gcc-options-shaped: 90% of the greedy-achievable improvement).",
        "`baseline` is the reference's search stack run faithfully",
        "(AUC-bandit portfolio, no surrogate); `surrogate` adds the GP",
        "surrogate plane: EI top-k batch concentration plus",
        "EI-maximizing proposal batches from an oversampled pool",
        "(surrogate/manager.py propose_pool) every other acquisition.",
        "Mode names describe the ALGORITHM stack, not the platform the",
        "sweep ran on (pre-round-3 artifacts said `tpu` for the",
        "surrogate stack).",
        f"{seeds_txt} seeds per cell.  Regenerate (one mode at a time is",
        "fine; aggregate rows persist in benchreport_rows.jsonl):",
        "`python scripts/benchreport.py --seeds 30 [--modes surrogate]",
        "--state benchreport_state.jsonl --rows benchreport_rows.jsonl",
        "--out BENCHREPORT.md`.",
        "",
        "| problem | mode | median iters | IQR | censored/seeds |",
        "|---|---|---|---|---|",
    ]
    ratios = {}
    for r in rows:
        lines.append(
            f"| {r['problem']} | {r['mode']} | {r['median_iters']:.0f} "
            f"| {r['iqr'][0]:.0f}-{r['iqr'][1]:.0f} "
            f"| {r['censored']}/{r['seeds']} |")
        ratios.setdefault(r["problem"], {})[r["mode"]] = r
    lines += ["", "## Ratios (north star: surrogate <= 50% of baseline)",
              "",
              "Censored runs count at the full budget, which FLATTERS a",
              "mode that censors more — so each ratio line also carries",
              "the solve-rate (seeds that reached the threshold within",
              "budget); read both together.", ""]
    for prob, m in ratios.items():
        for smode in ("surrogate", "surrogate-bandit"):
            if "baseline" in m and smode in m \
                    and m["baseline"]["median_iters"]:
                b, s = m["baseline"], m[smode]
                ratio = s["median_iters"] / b["median_iters"]
                sr_s = s["seeds"] - s["censored"]
                sr_b = b["seeds"] - b["censored"]
                lines.append(
                    f"* **{prob}**: {s['median_iters']:.0f} / "
                    f"{b['median_iters']:.0f} = **{ratio:.2f}** "
                    f"({smode}; solve-rate {sr_s}/{s['seeds']}, "
                    f"baseline {sr_b}/{b['seeds']})")
    if any(r["censored"] for r in rows):
        lines += [
            "",
            "Censored runs record the eval budget as their iteration",
            "count, which DEFLATES the censored mode's median: a ratio",
            "computed against a mode with nonzero censored/seeds",
            "understates that mode's true cost (it never solved those",
            "seeds at all).  Per-problem solve rates:",
            "",
        ]
        for r in rows:
            if r["censored"]:
                lines.append(
                    f"* {r['problem']} / {r['mode']}: solved "
                    f"{r['seeds'] - r['censored']}/{r['seeds']} seeds "
                    f"within budget")
    if any(r["problem"].startswith("gcc-real") for r in rows):
        lines += ["", GCC_REAL_ANALYSIS, "", SCREENING_NOTE]
    if any(r["mode"] == "surrogate-bandit" for r in rows):
        lines += ["", BANDIT_ARBITRATION_NOTE]
    pool_note = pool_utilization_note()
    if pool_note:
        lines += ["", pool_note]
    lines += ["", AB_PORTFOLIO_NOTE]
    lines.append("")
    return "\n".join(lines)


def pool_utilization_note():
    """WorkerPool.stats() surfaced in the report (ISSUE 7 satellite):
    the evaluation pool computes launched / dead-worker replacements /
    busy slot-seconds / utilization for every program-mode run, and the
    bench artifacts embed them — but no report ever showed them, so
    the async pipeline's scoreboard (how full the build slots actually
    ran) stayed invisible.  Reads the committed BENCH_CACHE.json
    runs; '' when the artifact is absent (e.g. a fresh checkout)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_CACHE.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return ""
    lines = [
        "## Evaluation-plane utilization (WorkerPool.stats())",
        "",
        "Slot-seconds the subprocess build pool spent running trials,",
        "from the committed BENCH_CACHE.json protocol (run 1 builds +",
        "populates the store; run 2 replays and serves from it — its",
        "pool sits idle BY DESIGN, that is the build elimination).",
        "utilization = busy_s / (wall x slots); the gap to 1.0 in a",
        "build run is dispatch overhead prefetch failed to hide.",
        "Per-run live numbers: the `[ut] pool utilization=` line, or",
        "`ut --trace out.json` for per-slot build lanes",
        "(docs/OBSERVABILITY.md).",
        "",
        "| run | launched | replaced | busy_s | utilization |",
        "|---|---|---|---|---|",
    ]
    for run in ("run1", "run2"):
        p = doc.get(run, {}).get("pool")
        if not p:
            return ""
        lines.append(
            f"| {run} ({'build' if run == 'run1' else 'serve'}) "
            f"| {p['launched']} | {p['replaced']} | {p['busy_s']} "
            f"| {p['utilization']} |")
    return "\n".join(lines)


SCREENING_NOTE = """\
## Cross-payload screening on gcc-real (r5)

The r4 diagnosis said the GP stays prior-dominated at 80 evals over
~1,100 one-hot lanes.  The r5 attack is TRANSFER (surrogate/screen.py):
per-flag sensitivity mined from nine full-80-eval archives of the
OTHER payloads over the same mined space (`exp_archives/`, three seeds
x {qsort, mmm, stencil}), used to (a) restrict or reweight the
surrogate's feature view and (b) bias the proposal plane's flip moves.
Protocol identical to the r4f arm (bandit arbitration, 8-eval pulls,
seeds 1000+, 0.78x-O2 threshold, budget 80); rows in
`exp_screen_gccreal.jsonl`.

| qsort arm (matched seeds) | median iters | IQR | censored |
|---|---|---|---|
| baseline (r4e, 30 seeds) | 28.5 | 18-66 | 3/30 |
| bandit-arbitrated, unscreened (r4f, 30 seeds) | 25 | — | 2/30 |
| hard screen 16 cont + 24 groups (112/1027 lanes, 30 seeds) | 28 | 17-46 | 2/30 |
| soft ARD reweighting, same sensitivities (10-seed pilot) | 28 | 19-43 | 0/10 |

**Neither transfer variant wins on qsort.**  Per-seed traces show
why: the easy half of the seed list solves inside the seeded bandit's
first batches before the GP ever fits (identical iters across all
arms), and on the hard tail the screened arms track the unscreened
one — except where the transfer actively hurts (hard: seed 1013
10 -> 46, lanes qsort needed were cut; soft: seeds 1008/1009
17 -> 47 / 14 -> 30, down-weighted lanes lost resolution).  The
mechanism: mmm/stencil solve in 7-8 iters, so their full-budget
archives mostly sample the solved region and carry little gradient
about the flags that matter for qsort's branchy code — flag
sensitivity is payload-specific, and importing it is importing the
wrong prior.  (Seeds 1001-1002 of the hard arm first ran under
background load; both were re-measured on an idle box and the jsonl
rows replaced — seed 1002 improved 80-censored -> 47, the median is
unchanged.)

A third, transfer-free variant was also measured: `flip_bias='online'`
(`--surrogate-flip-bias online`) re-ranks categorical groups by
|Pearson r| over THE RUN'S OWN observations at each refit and biases
only the plane's flip moves — no model narrowing, no foreign prior.
At 10 matched seeds it is per-seed IDENTICAL to the unscreened
bandit-arbitrated arm (median 18, 0/10 censored, exp_online_flip1.log):
with ~16-80 observations the within-run correlation signal is too weak
to move the 8-eval pulls off the unbiased trajectory.  Harmless, not
helpful; default stays 'none'.

The capability ships (it is the right tool when source and target
workloads genuinely share structure — `--surrogate-screen`, hard and
soft modes, both measured above), but the measured qsort rows keep it
OFF by default: no screening configuration is applied unless the user
passes archives."""


BANDIT_ARBITRATION_NOTE = """\
## Bandit-arbitrated plane (arbitration='bandit', r4)

`surrogate-bandit` rows measure the proposal plane as a credit-earning
VIRTUAL ARM of the AUC bandit (driver `register_virtual_arm`) instead
of the fixed every-other-acquisition schedule; `auto_passive` is pinned
off so the plane is always active (a no-op on these synthetic budgets).

Measuring the first (pre-parity) configuration exposed a real credit
interaction: 8-eval pool pulls inflate the arm's AUC use_count ~4x
faster per evaluation than ~32-eval technique batches, so once new
bests thin out near the optimum the exploration term
sqrt(2*log2(n)/use_count) ranks the plane LAST exactly when its local
refinement is the move that finishes the run.  rosenbrock-4d, 10
seeds, by pool batch (exp_bandit_batch.jsonl; scheduled plane: 346
median, 0/30 censored):

| pool batch | median iters | censored |
|---|---|---|
| 8 (pre-parity) | 2436 | 4/10 |
| 16 | 1470 | 4/10 |
| 32 | 414 | 2/10 |

The monotone recovery pins the mechanism, and the driver now applies
PULL-SIZE PARITY under bandit arbitration: the pool batch is raised to
the median technique-arm batch (`propose_batch_parity=False` opts
out).  The surrogate-bandit table rows are measured under parity.

Positioning: the scheduled plane remains the shipping default — it
still leads the synthetic sweep — and the run-budget passivation rule
applies in both arbitration modes (pull-size-parity pool tickets are
unaffordable on tiny budgets no matter who chooses them).  Bandit
arbitration is the opt-in robustness mode for the regime the static
rule cannot see: budgets large enough to afford the plane on a
landscape where it happens not to pay — there the AUC credit starves
it per-run instead of letting it displace technique batches."""


AB_PORTFOLIO_NOTE = """\
## Portfolio A/B: CMA-ES arm (matched 30 seeds)

`AUCBanditMetaTechniqueTPU` (portfolio A with the UniformGreedyMutation
arm swapped for batched CMA-ES) LOSES to portfolio A on the matched
30-seed rosenbrock-4d protocol: median 3916 vs 2412 iters (ratio 1.62),
solve-rate 15/30 vs 16/30 — full table in `AB_PORTFOLIO.md`
(regenerate: `python scripts/ab_portfolio.py`).  It stays opt-in;
portfolio A remains the default."""


# Committed analysis (VERDICT r3 next-step #2's accepted alternative):
# lives here, not as a hand-edit of BENCHREPORT.md, so regeneration
# preserves it.  Raw three-arm data: benchreport_state_r4.jsonl
# (baseline + surrogate, matched seeds 1000-1009, traces + thresholds
# per row) and diag_noprune.jsonl (prune-disabled arm, same seeds).
GCC_REAL_ANALYSIS = """\
## Why the surrogate does not beat the bandit on gcc-real (analysis)

![gcc-real convergence, 10 matched seeds](docs/img/gccreal_r4.png)
(regenerate: `python scripts/plot_gccreal.py`)

Protocol v2 (both modes seeded with the declared-defaults -O2 trial,
solved = 22% under the -O2 anchor, 80-eval budget, 10 matched seeds)
measured five arms on the qsort payload:

| arm | median iters | IQR | censored |
|---|---|---|---|
| baseline (seeded AUC bandit) | 19.5 | 16-30 | 1/10 |
| surrogate, in-loop guidance forced on (EI prune + pool) | 29 | 18-47 | 0/10 |
| ...with the prune disabled (pool only) | 28 | 20-71 | 2/10 |
| surrogate, shipping config (budget rule → passive here) | 18 | 14-26 | 1/10 |
| surrogate, bandit arbitration (no budget rule, 8-eval pulls) | 18 | 14-26 | 0/10 |

The r4 30-matched-seed re-measurements (fresh per-process anchors,
measured tighter on an idler box, so absolute medians sit higher than
this 10-seed table; per-run traces + thresholds stored in the state
files):

| arm (30 seeds) | median iters | censored |
|---|---|---|
| baseline (seeded AUC bandit) | 28.5 | 3/30 |
| surrogate, shipping config (budget rule → passive) | 28 | 4/30 |
| surrogate, bandit arbitration (no budget rule, 8-eval pulls) | **25** | **2/30** |

Parity between the first two holds at triple the seeds (0.98).  The
bandit-arbitrated arm — `uptune_tpu.calibrated.BUDGET_CONSTRAINED_OPTS`
as `surrogate_opts` (CLI: `--learning-models gp
--surrogate-arbitration bandit-small-budget`), i.e. the calibrated
plane with the AUC credit deciding and affordable 8-eval pulls, no
passivation — is the best measured
configuration on this workload: **0.88× baseline** with the best
solve-rate (28/30, `exp_bandit_gccreal_r4f.jsonl`).  Sparse
credit-gated pool pulls add cheap diversity on the hard tail that the
always-on plane (29 median) turns into displacement damage and the
passive plane forgoes.  On the fast-solving payloads the recipe is
harmless by construction and by measurement (10 seeds each,
`exp_recipe_safety.jsonl`): mmm 6.5 median vs 7 baseline, stencil 7
vs 8, zero censored.  As of r5 this recipe IS the default in its
regime: the run-budget rule applies it automatically whenever
budget < params and the root technique can arbitrate (see below).

The fifth arm (r4, `exp_bandit_gccreal.jsonl`) is the adaptive answer
to the same finding: arbitration='bandit' with the budget rule
disabled and pull-size parity off.  The AUC credit does in-run what
the static rule does a-priori — the plane gets tried after it fits,
earns no new-best events on this landscape, and is starved — landing
at the passive arm's median with the best solve-rate of any arm
(10/10).  In r5 this stopped being opt-in: the run-budget rule now
wires it as the default small-budget behavior, and explicit
arbitration='bandit' also covers the regime the static rule cannot
see — budgets large enough to afford the plane on a landscape where
it happens not to pay.

Three observations pin the mechanism:

1. On seeds that solve in ≤20 evals, the surrogate rows are IDENTICAL
   to baseline — the GP first fits at 16 points, so fast seeds never
   see it.  The surrogate can only influence the hard tail.
2. On the hard tail it is actively harmful in both variants: the
   damage is not the prune (disabling it does not recover baseline),
   it is the plane itself.  Pool tickets are 8-eval EI-ranked local
   flips that displace ~30-eval bandit batches, so each pool
   acquisition narrows per-eval diversity exactly when diversity is
   what solves the seed; and with ≤80 observations over 328 parameters
   (1123 surrogate features) the GP posterior is prior-dominated in
   almost every direction, so its EI ranking of candidate flips is
   noise wearing a confidence interval.
3. The bandit's own NormalGreedyMutation applies far bolder moves
   (σ=0.1 on unit lanes flips a large fraction of the 233 categorical
   lanes per candidate) — on this payload the landscape rewards bold
   exploration from the -O2 seed, not model-guided refinement.

The stored traces make the conclusion threshold-independent (each
run's iters re-scored post-hoc against its own anchor, no recompiles):

| target under -O2 | baseline median (cens) | surrogate median (cens) | ratio |
|---|---|---|---|
| 15% | 18.0 (1) | 18.0 (0) | 1.00 |
| 20% | 18.0 (1) | 18.0 (0) | 1.00 |
| 22% | 19.5 (1) | 29.0 (0) | 1.49 |
| 25% | 19.5 (2) | 36.5 (1) | 1.87 |

At shallow targets the modes are indistinguishable (both solve inside
the pre-surrogate window); the deeper the target — i.e. the more the
hard tail matters — the worse the surrogate plane does.  The penalty
is monotone in exactly the regime a useful model would have to win.

What actually won on the real workload is protocol v2's seeding: last
round's unseeded runs took 63-75 median iters to a SHALLOWER (15%)
target; the seeded bandit reaches a DEEPER (22%) target in ~20.  That
matches the reference's own design: OpenTuner's recommended
configuration for compiler flags is the bandit portfolio, with
learned models as offline estimators rather than in-loop gatekeepers.
The surrogate plane's wins are real where structure and budget allow
(0.13-0.46x on rosenbrock/gcc-options-shaped spaces, thousands of
evals over ≤200 params).  The shipping behavior encodes the finding as
a RUN-BUDGET RULE, upgraded in r5 to pick the measured-best recipe
itself: when the eval budget is smaller than the scalar parameter
count, the driver switches the plane to bandit arbitration with its
affordable 8-eval pulls (BUDGET_CONSTRAINED_OPTS semantics — the 0.88×
best-solve-rate configuration above) whenever the root technique is an
AUC bandit, and falls back to the old passivation (observe + fit only)
when the plane cannot be arbitrated; both paths warn loudly and
`auto_passive: False` opts out.  A default `--learning-models gp` run
on gcc-real therefore now measures the bandit-arbitrated arm with no
extra flag (r5 table row; the r4 "surrogate" rows were measured under
passivation — state-file sigs carry `budget_rule=v2` so the two
protocols never merge).  An observation-count gate was tried and
rejected: gating on points-so-far also withheld guidance where it
pays (gcc-options: 1553 gated vs 1046.5 ungated 5-seed median), so the
budget, not the dimension alone, is the discriminating variable.
The mmm payload corroborates the budget argument from the other side:
it solves in ≤7 median evals — before the surrogate would activate —
so both modes measure identically (ratio 1.0).  The third payload,
gcc-real-stencil (SIMD-bound integer stencil, ~33% under -O2 reachable
via the -O3/vectorizer flag family), lands the same way: 8 median
evals, 10/10 solved in both modes (0.94) — across all three real
optimization profiles (branchy search, cache-blocked matmul,
vectorizable stencil), the seeded bandit solves the 22%-under-O2 bar
inside or barely past its first batches, leaving a passive-plane
surrogate mode at parity and no room where in-loop guidance could pay.
"""


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import cpuenv  # noqa: F401  (hang-proof platform)
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=30)
    ap.add_argument("--quick", action="store_true",
                    help="3 seeds, smaller budgets, rosenbrock-2d only")
    ap.add_argument("--problems", nargs="*", default=None)
    ap.add_argument("--modes", nargs="*",
                    default=["baseline", "surrogate"],
                    choices=["baseline", "surrogate", "surrogate-bandit",
                             "tpu"],
                    help="'tpu' is the legacy name for 'surrogate'; "
                         "'surrogate-bandit' is the same plane under "
                         "AUC-bandit arbitration (r4)")
    ap.add_argument("--out", default=None, help="write markdown here")
    ap.add_argument("--state", default=None,
                    help="per-run checkpoint jsonl (resume after crash)")
    ap.add_argument("--rows", default=None,
                    help="aggregate-rows jsonl: rows for modes NOT being "
                         "re-run are loaded from here, and all rows are "
                         "written back — lets one mode be re-measured "
                         "without redoing the other's sweep")
    args = ap.parse_args()
    args.modes = sorted({_norm_mode(m) for m in args.modes})
    problems = args.problems or (
        ["rosenbrock-2d"] if args.quick else list(DEFAULT_PROBLEMS))
    seeds = 3 if args.quick else args.seeds
    rows = run_suite(problems, seeds,
                     budget_scale=0.5 if args.quick else 1.0,
                     state_path=args.state, modes=args.modes)
    if args.rows:
        prior = []
        if os.path.exists(args.rows):
            with open(args.rows) as f:
                prior = [json.loads(ln) for ln in f if ln.strip()]
            for r in prior:
                r["mode"] = _norm_mode(r["mode"])
        if args.quick and any(
                r["problem"] in PROBLEM_BUDGETS
                and r.get("budget") == PROBLEM_BUDGETS[r["problem"]]
                for r in prior):
            # a --quick invocation must never displace full-budget rows
            # from the published rows file: half-budget aggregates would
            # silently become the source for the next --out regeneration.
            # Divert this invocation's rows AND report to side files.
            quick_rows = args.rows + ".quick"
            print(f"rows: {args.rows} holds full-budget rows; --quick "
                  f"results diverted to {quick_rows} (published rows and "
                  f"--out untouched)", file=sys.stderr)
            with open(quick_rows, "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
            if args.out:
                with open(args.out + ".quick", "w") as f:
                    f.write(to_markdown(rows, seeds))
                print(f"wrote {args.out}.quick", file=sys.stderr)
            sys.exit(0)
        fresh = {(r["problem"], r["mode"]) for r in rows}
        scale = 0.5 if args.quick else 1.0
        kept, dropped = [], []
        for r in prior:
            if (r["problem"], r["mode"]) in fresh:
                dropped.append(r)  # superseded by this invocation
                continue
            # the same staleness guards as the per-run state file:
            # never merge rows measured at another budget or under
            # other tpu-mode settings into the published table
            cur_budget = (int(PROBLEM_BUDGETS[r["problem"]] * scale)
                          if r["problem"] in PROBLEM_BUDGETS else None)
            if (r.get("budget") != cur_budget
                    or r.get("sopts_sig") != _sopts_sig(r["mode"], r["problem"])
                    or r.get("proto") != PROBLEM_PROTO.get(r["problem"])):
                dropped.append(r)
            else:
                kept.append(r)
        if dropped:
            # excluded rows are preserved, not destroyed: a --quick
            # invocation pointed at the published rows file must never
            # delete the 30-seed sweep results it mismatches.  Append
            # only rows not already preserved — every re-generation
            # supersedes the same aggregates, and blind appends tripled
            # rows in the archive (r4 review)
            stale_path = args.rows + ".stale"
            have = set()
            if os.path.exists(stale_path):
                with open(stale_path) as f:
                    have = {line.rstrip("\n") for line in f}
            with open(stale_path, "a") as f:
                for r in dropped:
                    line = json.dumps(r)
                    if line not in have:
                        f.write(line + "\n")
                        have.add(line)
                    print(f"rows: excluded {r['problem']}/{r['mode']} "
                          f"(budget/settings mismatch or superseded); "
                          f"preserved in {stale_path}",
                          file=sys.stderr)
        rows = kept + rows
        order = {p: i for i, p in enumerate(PROBLEMS)}
        rows.sort(key=lambda r: (order.get(r["problem"], len(order)),
                                 r["mode"]))
        with open(args.rows, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
    if args.out:
        with open(args.out, "w") as f:
            f.write(to_markdown(rows, seeds))
        print(f"wrote {args.out}", file=sys.stderr)
