"""Synthetic polynomial with covariates for causal discovery — the
shape of the reference sample (/root/reference/samples/causal-graph/
poly.py:1-17): two intermediate quantities are registered as
`ut.feature` covariates; after tuning, NOTEARS over the archived
covariates + QoR identifies which one drives the objective.

Tune:     ut samples/causal-graph/poly.py -pf 2 --test-limit 60
Analyze:  python -c "from uptune_tpu.plugins import covariate_graph; ..."
          (see tests/test_notears.py::TestCovariateGraph)
"""
import uptune_tpu as ut

x = ut.tune(2, (2, 15), name="x")
y = ut.tune(5, (2, 12), name="y")
a = ut.tune(2, (2, 15), name="a")
b = ut.tune(5, (2, 12), name="b")

# expected causal graph: ab -> res <- xy
xy = x * y + x * x
ab = a * a + b * b + a * b

res = ab - xy
ut.feature(ab, "ab")
ut.feature(xy, "xy")

ut.target(res, "max")
