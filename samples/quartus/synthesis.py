"""Quartus synthesis/fit option tuning — the shape of the reference's
quartus sample (/root/reference/samples/quartus/synthesis.py:1-302:
~13 tuned synth+fit options, feature extraction from STA/syn/fit
reports feeding `ut.feature` covariates, QoR = timing slack).

Runs against `mock_flow.py` (a deterministic stand-in emitting
real-format report files) so the full option->flow->report->extract->
covariate->QoR loop works without licensed tools; point `FLOW` at a
real quartus_sh wrapper to tune actual hardware builds.

    ut samples/quartus/synthesis.py -pf 2 --test-limit 40
"""
import json
import os
import subprocess
import sys
import tempfile

import uptune_tpu as ut

DESIGN = "mm8x8"
HERE = os.path.dirname(os.path.abspath(__file__))
FLOW = [sys.executable, os.path.join(HERE, "mock_flow.py")]

opts = {
    "seed": ut.tune(1, (1, 64), name="seed"),
    "fitter_effort": ut.tune("auto", ["fast", "auto", "high"],
                             name="fitter_effort"),
    "physical_synthesis": ut.tune(False, name="physical_synthesis"),
    "mux_restructure": ut.tune("auto", ["off", "on", "auto"],
                               name="mux_restructure"),
    "max_lut_depth": ut.tune(6, (3, 9), name="max_lut_depth"),
}

workdir = tempfile.mkdtemp(prefix="quartus_")
subprocess.run(FLOW + [DESIGN, workdir, json.dumps(opts)], check=True,
               timeout=600)

# extract report features -> covariates (report.py:163-174 semantics)
vec = ut.quartus(DESIGN, workdir)
print(f"slack={vec['slack']:.3f} alms={vec.get('Logic utilization (in ALMs)')}")

ut.target(vec["slack"], "max")
