"""Deterministic mock Quartus flow: consumes the tuned options from the
environment (written by synthesis.py) and emits STA/syn/fit report
files in the real Quartus text formats, so the whole report-extraction
path (uptune_tpu.api.features) is exercised without licensed tools.

The QoR model: slack improves with higher effort/seed luck and
aggressive physical synthesis, resources grow with effort — shaped like
the tradeoffs the reference tunes (samples/quartus/synthesis.py:1-302).
"""
import json
import os
import sys


def run(design: str, workdir: str, opts: dict) -> None:
    seed = int(opts.get("seed", 1))
    effort = {"fast": 0.0, "auto": 0.5, "high": 1.0}[
        opts.get("fitter_effort", "auto")]
    physopt = 1.0 if opts.get("physical_synthesis", False) else 0.0
    mux = {"off": 0.0, "on": 0.3, "auto": 0.15}[
        opts.get("mux_restructure", "auto")]
    lut = int(opts.get("max_lut_depth", 6))

    # deterministic "luck" per seed
    luck = ((seed * 2654435761) % 997) / 997.0
    slack = (-1.5 + 1.2 * effort + 0.6 * physopt + 0.4 * mux
             + 0.35 * luck - 0.08 * abs(lut - 5))
    tns = min(0.0, slack) * 120.0
    alms = int(10000 * (1.0 + 0.25 * effort + 0.15 * physopt))
    regs = int(8000 * (1.0 + 0.1 * effort))
    ffs = int(regs * 1.1)

    with open(os.path.join(workdir, f"{design}.sta.syn.summary"),
              "w") as f:
        f.write("Type  : setup\n")
        f.write(f"Slack : {slack:.3f}\n")
        f.write(f"TNS : {tns:.1f}\n")
    with open(os.path.join(workdir, f"{design}.syn.rpt"), "w") as f:
        f.write(f"; boundary_port ; {240} ;\n")
        f.write(f"; fourteennm_ff ; {ffs:,} ;\n")
        f.write(f"; fourteennm_lcell_comb ; {alms:,} ;\n")
        f.write(f"; Max LUT depth ; {lut}.00 ;\n")
        f.write(f"; Average LUT depth ; {lut * 0.6:.2f} ;\n")
    with open(os.path.join(workdir, f"{design}.fit.syn.summary"),
              "w") as f:
        f.write(f"Logic utilization (in ALMs) : {alms:,} / 100,000\n")
        f.write(f"Total dedicated logic registers : {regs:,}\n")
        f.write("Total pins : 120 / 500\n")
        f.write(f"Total block memory bits : {alms * 12:,}\n")
        f.write("Total RAM Blocks : 24 / 99\n")
        f.write("Total DSP Blocks : 12 / 48\n")


if __name__ == "__main__":
    design = sys.argv[1]
    workdir = sys.argv[2]
    opts = json.loads(sys.argv[3])
    run(design, workdir, opts)
