"""ABC logic-synthesis recipe tuning — the shape of the reference
sample (/root/reference/samples/abc-options/abc.py:1-25: a sequence of
optimization passes plus `resub -K`), over a deterministic synthetic
recipe model since no ABC binary ships in this image.

The space: an ordering of 8 optimization passes (PermParam) plus the
resub cut size K and two enum knobs.  The synthetic cost rewards known
good pass adjacencies (e.g. `balance` early, `rewrite` before `refactor`)
— structurally the pass-interaction landscape real recipes exhibit.

    ut samples/abc-options/abc.py -pf 2 --test-limit 80
"""
import uptune_tpu as ut

PASSES = ("balance", "rewrite", "rewrite -z", "refactor",
          "refactor -z", "resub", "dc2", "dch")

order = ut.tune(list(PASSES), list(PASSES), name="recipe")
k = ut.tune(8, (4, 16), name="resub_k")
lutsize = ut.tune(6, [4, 6], name="lut_size")
effort = ut.tune("fast", ["fast", "deep"], name="effort")

pos = {p: i for i, p in enumerate(order)}
cost = 100.0
cost -= 8.0 * (len(PASSES) - 1 - pos["balance"])       # balance early
cost -= 4.0 * max(0, pos["refactor"] - pos["rewrite"])  # rewrite first
cost -= 3.0 * max(0, pos["resub"] - pos["dc2"])         # resub after dc2
cost += 0.5 * abs(k - 10)                               # sweet spot K=10
cost += 2.0 if lutsize == 4 else 0.0
cost -= 1.5 if effort == "deep" else 0.0

ut.target(cost, "min")
print("recipe:", "; ".join(order), f"K={k} cost={cost:.1f}")
