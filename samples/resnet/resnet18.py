"""ResNet-18 FPGA schedule tuning — the shape of the reference sample
(/root/reference/samples/resnet/resnet18.py: choose HeteroCL scheduling
primitives per conv layer for an FPGA backend), over a deterministic
synthetic latency model since HeteroCL and an FPGA toolchain are not in
this image.

Per conv stage: a scheduling primitive (baseline / reorder / tile /
unroll+pipeline), a pow2 tile size, and an unroll factor.  The model
rewards pipelining late (wide) layers and tiling early (large-feature)
layers — the split real schedules converge to — under a LUT budget that
rules out unrolling everything.

    ut samples/resnet/resnet18.py -pf 2 --test-limit 200
"""
import uptune_tpu as ut

# (name, feature-map size, channels) for the 8 residual-block stages
STAGES = [("c1", 56, 64), ("c2", 56, 64), ("c3", 28, 128),
          ("c4", 28, 128), ("c5", 14, 256), ("c6", 14, 256),
          ("c7", 7, 512), ("c8", 7, 512)]
LUT_BUDGET = 120_000

total_lat = 0.0
total_lut = 0.0
for name, fmap, ch in STAGES:
    prim = ut.tune("baseline",
                   ["baseline", "reorder", "tile", "pipeline"],
                   name=f"{name}_prim")
    tile = ut.tune(8, [4, 8, 16, 32], name=f"{name}_tile")
    unroll = ut.tune(1, [1, 2, 4, 8], name=f"{name}_unroll")

    work = fmap * fmap * ch * 9.0 / 1e3          # MACs (scaled)
    lat = work
    lut = 2000.0
    if prim == "reorder":
        lat *= 0.85
    elif prim == "tile":
        # tiling pays off on large feature maps when the tile fits
        lat *= 0.55 if fmap >= 28 and tile <= fmap // 2 else 0.95
        lut += 60 * tile
    elif prim == "pipeline":
        # pipelining pays off on deep/narrow layers; area scales with
        # unroll
        lat *= (0.35 if fmap <= 14 else 0.8) / unroll
        lut += 900 * unroll + 40 * tile
    total_lat += lat
    total_lut += lut

# over-budget designs fail timing closure: steep penalty, as in real
# flows (the reference reports inf on failed builds)
qor = total_lat + max(0.0, total_lut - LUT_BUDGET) * 0.05

ut.target(qor, "min")
print(f"latency={total_lat:.1f} LUT={total_lut:.0f} qor={qor:.1f}")
