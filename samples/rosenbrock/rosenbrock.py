"""Rosenbrock — the reference's framework-test fixture
(/root/reference/samples/rosenbrock/rosenbrock.py:1-60) in intrusive
form.

    ut samples/rosenbrock/rosenbrock.py -pf 2 --test-limit 200

For the in-process (library-mode) equivalent with per-technique sweeps,
see scripts/benchreport.py and samples/py_api/api_example.py.
"""
import uptune_tpu as ut

DIM = 4
x = [ut.tune(0.0, (-2.048, 2.048), name=f"x{i}") for i in range(DIM)]

val = sum(100.0 * (x[i + 1] - x[i] ** 2) ** 2 + (1.0 - x[i]) ** 2
          for i in range(DIM - 1))
ut.target(val, "min")
print("rosenbrock:", val)
