"""Deterministic mock Quartus fit for the systolic-array sample:
emits Systolic_Array_8x8.sta.fit.summary in the real STA summary text
format the sample (and the reference, systolic-array/quartus.py:29-41)
parses.  Slack responds to effort/physical-synthesis options and
per-seed luck, like a real seed sweep."""
import hashlib
import json
import os
import sys


def run(workdir: str, opts: dict) -> None:
    seed = int(opts.get("seed", 1))
    luck_bytes = hashlib.sha256(
        json.dumps(opts, sort_keys=True).encode()).digest()
    luck = int.from_bytes(luck_bytes[:4], "big") / 2 ** 32
    seed_luck = ((seed * 2654435761) % 997) / 997.0

    slack = -0.9
    slack += {"Speed": 0.5, "Balanced": 0.25, "Area": 0.0}[
        opts["optimization_technique"]]
    slack += 0.3 if opts["physical_synthesis"] == "On" else 0.0
    slack += 0.2 if opts["fitter_effort"] == "Standard Fit" else 0.0
    slack += 0.15 if opts["synth_timing_driven_synthesis"] == "On" else 0
    slack += -0.2 if opts["synthesis_effort"] == "Fast" else 0.0
    slack += 0.35 * seed_luck + 0.1 * luck
    tns = min(0.0, slack) * 85.0

    with open(os.path.join(workdir,
                           "Systolic_Array_8x8.sta.fit.summary"),
              "w") as f:
        f.write("Type  : setup\n")
        f.write(f"Slack : {slack:.3f}\n")
        f.write(f"TNS : {tns:.1f}\n")


if __name__ == "__main__":
    run(sys.argv[1], json.loads(sys.argv[2]))
