"""Systolic-array Quartus option + seed sweep — the reference's
systolic-array sample (/root/reference/samples/systolic-array/
quartus.py: 10 global-assignment options written as options.tcl,
quartus_sh run, slack/TNS parsed out of the
Systolic_Array_8x8.sta.*.summary report).

Runs against `mock_flow.py` (deterministic, real STA summary format) by
default; set UT_QUARTUS_FLOW to a `flow workdir optsjson` wrapper for
real Quartus Pro.  QoR = -slack (maximize positive slack).

    ut samples/systolic-array/quartus.py -pf 2 --test-limit 30
"""
import json
import math
import os
import subprocess
import sys
import tempfile

import uptune_tpu as ut

HERE = os.path.dirname(os.path.realpath(__file__))
DESIGN = "Systolic_Array_8x8"

option = {
    "auto_dsp_recognition": ut.tune("On", ["On", "Off"]),
    "disable_register_merging_across_hierarchies":
        ut.tune("Auto", ["On", "Off", "Auto"]),
    "mux_restructure": ut.tune("Auto", ["On", "Off", "Auto"]),
    "optimization_technique":
        ut.tune("Balanced", ["Area", "Speed", "Balanced"]),
    "synthesis_effort": ut.tune("Auto", ["Auto", "Fast"]),
    "synth_timing_driven_synthesis": ut.tune("On", ["On", "Off"]),
    "fitter_aggressive_routability_optimization":
        ut.tune("Automatically", ["Always", "Automatically", "Never"]),
    "fitter_effort": ut.tune("Auto Fit", ["Standard Fit", "Auto Fit"]),
    "remove_duplicate_registers": ut.tune("On", ["On", "Off"]),
    "physical_synthesis": ut.tune("Off", ["On", "Off"]),
    "seed": ut.tune(1, (1, 64), name="seed"),
}

workdir = tempfile.mkdtemp(prefix="ut_systolic_")
# options.tcl exactly as the reference writes it
with open(os.path.join(workdir, "options.tcl"), "w") as f:
    for k, v in option.items():
        if k == "seed":
            f.write(f'set_global_assignment -name SEED {v}\n')
        else:
            f.write(f'set_global_assignment -name "{k}" "{v}"\n')

flow = os.environ.get("UT_QUARTUS_FLOW")
if flow:
    subprocess.run([flow, workdir, json.dumps(option)], check=False,
                   timeout=float(os.environ.get("UT_QUARTUS_TIMEOUT",
                                                7200)))
else:
    subprocess.run([sys.executable, os.path.join(HERE, "mock_flow.py"),
                    workdir, json.dumps(option)], check=True, timeout=600)


# slack/TNS via the library extractor (api/features.py get_timing,
# exported through ut.quartus): handles 'None' entries and partial
# summaries instead of crashing the trial
from uptune_tpu.api.features import get_timing  # noqa: E402

try:
    slack, tns = get_timing(DESIGN, workdir, "fit")
except OSError:
    slack = None
if slack is None:
    ut.target(math.inf, "min")
else:
    ut.target(-float(slack), "min")   # maximize slack
    print(f"seed={option['seed']} slack={float(slack):.3f} "
          f"tns={float(tns):.1f}")
