"""Super-Mario-style button-sequence tuning — the shape of the
reference's mario sample (/root/reference/samples/mario/mario.py: tune a
button-press movie, replay it in an NES emulator, maximize distance
travelled before death), over a deterministic mini-platformer since no
emulator ships in this image.

The space: one action per time slot (run / short hop / long jump).  The
course is a fixed sequence of gaps and walls; kinematics are integer
steps.  Falling into a gap ends the run; bonking a wall costs the cell
(a later hop can still clear it) — fitness is distance covered,
maximized.  Like the real thing, late slots only matter if the early
slots survive, giving the long-horizon credit landscape the emulator
version exhibits.

    ut samples/mario/mario.py -pf 2 --test-limit 300
"""
import uptune_tpu as ut

SLOTS = 24
# course features by x-position: gaps must be jumped over, walls need a
# hop exactly at the approach cell
GAPS = {7, 8, 19, 20, 21, 33, 46, 47}
WALLS = {13, 27, 40}
COURSE_LEN = 56

actions = [ut.tune("run", ["run", "hop", "jump"], name=f"a{i}")
           for i in range(SLOTS)]

x = 0
air = 0          # cells of airtime remaining
dist = 0
for a in actions:
    if air == 0:
        if a == "hop":
            air = 2
        elif a == "jump":
            air = 4
    step = 2 if air else 1          # airborne carries momentum
    for _ in range(step):
        x += 1
        if x >= COURSE_LEN:
            break
        if x in GAPS and air == 0:
            x = -1                  # fell: run over
            break
        if x in WALLS and air == 0:
            x -= 1                  # bonk: lose the cell
            break
    if x < 0 or x >= COURSE_LEN:
        break
    air = max(0, air - 1)
    dist = max(dist, x)

fitness = COURSE_LEN if x >= COURSE_LEN else max(0, dist)
ut.target(float(fitness), "max")
print(f"distance {fitness}/{COURSE_LEN}")
