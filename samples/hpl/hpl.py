"""HPL (Linpack) HPL.dat tuning — the shape of the reference sample
(/root/reference/samples/hpl/hpl.py: 13 IntegerParameters rendered into
HPL.dat via a Mako template, minimizing measured solve time), over a
deterministic synthetic performance model since no xhpl/MPI stack ships
in this image.

The space mirrors the reference's manipulator one-for-one (blocksize,
pmapping, pfact, nbmin, ndiv, rfact, bcast, depth, swap,
swapping_threshold, L1/U transposed, mem_alignment).  The synthetic
model rewards the interactions real HPL runs exhibit: a blocksize sweet
spot that shifts with depth, bcast algorithms that only pay off at
depth>0, and alignment/threshold penalties.

    ut samples/hpl/hpl.py -pf 2 --test-limit 150
"""
import uptune_tpu as ut

nb = ut.tune(1, (1, 64), name="blocksize")
pmap = ut.tune(0, (0, 1), name="row_or_colmajor_pmapping")
pfact = ut.tune(0, (0, 2), name="pfact")
nbmin = ut.tune(1, (1, 4), name="nbmin")
ndiv = ut.tune(2, (2, 2), name="ndiv")
rfact = ut.tune(0, (0, 4), name="rfact")
bcast = ut.tune(0, (0, 5), name="bcast")
depth = ut.tune(0, (0, 4), name="depth")
swap = ut.tune(0, (0, 2), name="swap")
swap_thresh = ut.tune(64, (64, 128), name="swapping_threshold")
l1t = ut.tune(0, (0, 1), name="L1_transposed")
ut_t = ut.tune(0, (0, 1), name="U_transposed")
align = ut.tune(4, (4, 16), name="mem_alignment")

# synthetic solve time (seconds): GEMM efficiency peaks at a
# depth-dependent blocksize; pipelined bcasts (4/5) only help with
# lookahead depth; panel factorization knobs interact mildly
best_nb = 28 + 6 * depth
t = 10.0 + 0.004 * (nb - best_nb) ** 2
t += 0.35 * abs(depth - 2)
t += (0.8 if bcast in (4, 5) and depth == 0 else 0.0)
t -= (0.6 if bcast in (4, 5) and depth >= 2 else 0.0)
t += 0.15 * pfact + 0.08 * abs(rfact - 2) + 0.05 * (nbmin - 1)
t += 0.002 * abs(swap_thresh - 96) + 0.2 * (swap == 0)
t += 0.25 * (align % 8 != 0) + 0.1 * (pmap == 1)
t -= 0.15 * (l1t == ut_t)

ut.target(t, "min")
print(f"NB={nb} depth={depth} bcast={bcast} -> t={t:.3f}s")
