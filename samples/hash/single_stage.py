"""Minimal single-stage black-box demo (the shape of the reference's
`samples/hash/single_stage.py:1-15`): tune multiplier/shift constants of
a toy hash over a fixed key set, minimizing bucket collisions."""
import uptune_tpu as ut

mult = ut.tune(31, (3, 1023), name="mult")
shift = ut.tune(4, (0, 16), name="shift")
buckets = ut.tune(64, [32, 64, 128, 256], name="buckets")

keys = [k * 2654435761 % (1 << 32) for k in range(257)]
seen = {}
collisions = 0
for k in keys:
    h = ((k * mult) >> shift) % buckets
    collisions += seen.get(h, 0)
    seen[h] = seen.get(h, 0) + 1

ut.target(float(collisions), "min")
