"""Template (non-intrusive) variant of the hash demo: the tunables are
declared in comment annotations; the source itself stays runnable as-is
(the reference's `samples/hash/single_stage_template.py:1-6` shape)."""
import uptune_tpu as ut

mult = 31       # {% mult = TuneInt(31, (3, 1023)) %}
shift = 4       # {% shift = TuneInt(4, (0, 16)) %}
buckets = 64    # {% buckets = TuneEnum(64, [32, 64, 128, 256]) %}

keys = [k * 2654435761 % (1 << 32) for k in range(257)]
seen = {}
collisions = 0
for k in keys:
    h = ((k * mult) >> shift) % buckets
    collisions += seen.get(h, 0)
    seen[h] = seen.get(h, 0) + 1

ut.target(float(collisions), "min")
