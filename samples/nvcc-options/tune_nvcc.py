"""NVCC compiler-flag tuning — the shape of the reference sample
(/root/reference/samples/nvcc-options/tune_nvcc.py: -use_fast_math,
--maxrregcount, optimization level etc. on CUDA kernels, minimizing
measured kernel time), over a deterministic synthetic occupancy model
since no CUDA toolchain ships in this image.

The space mirrors the reference's flags; the model captures the real
trade-off those flags move: register cap vs. occupancy vs. spills, fast
math vs. transcendental throughput, block size vs. tail effect.

    ut samples/nvcc-options/tune_nvcc.py -pf 2 --test-limit 150
"""
import uptune_tpu as ut

olevel = ut.tune("-O2", ["-O0", "-O1", "-O2", "-O3"], name="olevel")
fast_math = ut.tune(False, [True, False], name="use_fast_math")
maxrreg = ut.tune(64, (16, 255), name="maxrregcount")
block = ut.tune(128, [32, 64, 128, 256, 512, 1024], name="block_size")
ftz = ut.tune(False, [True, False], name="ftz")
prec_div = ut.tune(True, [True, False], name="prec_div")
lineinfo = ut.tune(False, [True, False], name="lineinfo")

KERNEL_REGS = 72        # natural register need of the kernel
SM_REGS = 65536

# occupancy: warps per SM limited by the register cap
regs = min(KERNEL_REGS, maxrreg)
spill = max(0, KERNEL_REGS - maxrreg)
warps = min(48, SM_REGS // (regs * 32), 2048 // block * (block // 32))
t = 100.0 / max(1, warps)                      # latency hiding
t += 0.35 * spill                              # local-memory spills
t += {"-O0": 3.0, "-O1": 1.0, "-O2": 0.0, "-O3": -0.2}[olevel]
t -= 1.2 if fast_math else 0.0
t -= 0.3 if ftz else 0.0
t += 0.5 if prec_div else 0.0                  # precise division is slow
t += 0.2 if lineinfo else 0.0                  # debug info inhibits opts
t += 0.8 if block >= 512 else 0.0              # tail effect on this grid

ut.target(t, "min")
print(f"{olevel} rreg={maxrreg} block={block} -> {t:.2f} ms")
