"""Vitis/Vivado implementation-step tuning — the reference's vivado
sample (/root/reference/samples/vivado/tune_vitis.py:26-151 +
options.py:12-74): kernel frequency plus the opt/place/phys-opt/route
directive and MORE-flag pool, written into Vitis config.ini files, QoR =
achieved post-route period (1000/freq - WNS, minimized).

Runs against `mock_flow.py` (real-format timing summary + csynth XML) by
default; set UT_VITIS_FLOW to a `run.sh workdir optsjson` wrapper for
actual builds.  The csynth XML feeds `ut.vhls(..., register=True)` so
area/latency covariates flow into the archive exactly as the
reference's `ut.feature` path intends.

    ut samples/vivado/tune_vitis.py -pf 2 --test-limit 40
"""
import json
import math
import os
import subprocess
import sys
import tempfile

import uptune_tpu as ut

HERE = os.path.dirname(os.path.realpath(__file__))

# option pool (options.py:12-74; first value = default)
OPTIONS = {
    "Frequency": (250, 500),
    "OPT_DESIGN.ARGS.DIRECTIVE": [
        "Explore", "ExploreArea", "AddRemap", "ExploreSequentialArea",
        "RuntimeOptimized", "NoBramPowerOpt", "ExploreWithRemap",
        "Default"],
    "PLACE_DESIGN.ARGS.DIRECTIVE": [
        "Explore", "WLDrivenBlockPlacement", "ExtraNetDelay_high",
        "ExtraNetDelay_low", "SSI_SpreadLogic_high",
        "SSI_SpreadLogic_low", "AltSpreadLogic_high",
        "AltSpreadLogic_medium", "AltSpreadLogic_low",
        "ExtraPostPlacementOpt", "ExtraTimingOpt", "SSI_SpreadSLLs",
        "SSI_BalanceSLLs", "SSI_BalanceSLRs", "SSI_HighUtilSLRs",
        "RuntimeOptimized", "Quick", "Default"],
    "PHYS_OPT_DESIGN.IS_ENABLED": ["true", "false"],
    "PHYS_OPT_DESIGN.ARGS.DIRECTIVE": [
        "Explore", "ExploreWithHoldFix", "ExploreWithAggressiveHoldFix",
        "AggressiveExplore", "AlternateReplication",
        "AggressiveFanoutOpt", "AddRetime", "AlternateFlowWithRetiming",
        "Default", "Disabled"],
    "ROUTE_DESIGN.ARGS.DIRECTIVE": [
        "Explore", "NoTimingRelaxation", "MoreGlobalIterations",
        "HigherDelayCost", "RuntimeOptimized", "AlternateCLBRouting",
        "Quick", "Default"],
    "ROUTE_DESIGN.ARGS.MORE.tns_cleanup": ["off", "on"],
    "POST_ROUTE_PHYS_OPT_DESIGN.IS_ENABLED": ["true", "false"],
    "POST_ROUTE_PHYS_OPT_DESIGN.ARGS.DIRECTIVE": [
        "AggressiveExplore", "Default"],
}
# first value = default, faithful to options.py:46-58 (fanout_opt
# defaults ON, every other MORE flag defaults off)
OPTIONS["PHYS_OPT_DESIGN.ARGS.MORE.fanout_opt"] = ["on", "off"]
for _flag in ("placement_opt", "routing_opt", "rewire",
              "critical_cell_opt", "dsp_register_opt",
              "bram_register_opt", "bram_enable_opt",
              "shift_register_opt", "retime", "critical_pin_opt",
              "clock_opt", "hold_fix"):
    OPTIONS[f"PHYS_OPT_DESIGN.ARGS.MORE.{_flag}"] = ["off", "on"]


def write_configs(workdir: str, option: dict) -> None:
    """Emit the Vitis hls/link config.ini pair (tune_vitis.py:26-80):
    per-stage STEPS properties, MORE-OPTIONS flag groups, disabled
    stages omitted."""
    with open(os.path.join(workdir, "hls_config.ini"), "w") as fp:
        fp.write(f"kernel_frequency={option['Frequency']}\n")
    with open(os.path.join(workdir, "link_config.ini"), "w") as fp:
        fp.write(f"kernel_frequency={option['Frequency']}\n[vivado]\n")
        disabled = {k.split(".")[0] for k, v in option.items()
                    if k.endswith("IS_ENABLED") and v == "false"}
        directed = set()
        for key, val in option.items():
            if key == "Frequency" or ".ARGS.MORE." in key:
                continue
            stage = key.split(".")[0]
            if key.endswith("IS_ENABLED") and val == "true":
                fp.write(f"prop=run.impl_1.STEPS.{key}={val}\n")
            elif key.endswith("ARGS.DIRECTIVE") and stage not in disabled \
                    and val != "Disabled":
                fp.write(f"prop=run.impl_1.STEPS.{key}={val}\n")
                directed.add(stage)
        flags_by_stage = {}
        for key, val in option.items():
            if ".ARGS.MORE." in key and val == "on":
                stage, flag = key.split(".ARGS.MORE.")
                flags_by_stage.setdefault(stage, []).append(flag)
        for stage, flags in flags_by_stage.items():
            # NOTE: like the reference config() (tune_vitis.py:65-72),
            # MORE flags are emitted only when the stage has no
            # directive; ROUTE_DESIGN always has one, so its
            # tns_cleanup knob only reaches builds when the directive
            # machinery is bypassed — kept for space parity, and the
            # mock flow deliberately reads it so search behavior over
            # the knob is still exercised
            if stage in disabled or stage in directed:
                continue
            joined = " ".join("-" + fl for fl in flags)
            fp.write("prop=run.impl_1.{{STEPS.{}.MORE OPTIONS}}="
                     "{{{}}}\n".format(stage, joined))


def parse_wns(rpt_path: str) -> float:
    """WNS from the post-route timing summary: first number six lines
    under 'Design Timing Summary' (tune_vitis.py:126-139)."""
    with open(rpt_path) as fp:
        content = fp.readlines()
    for i, line in enumerate(content):
        if "Design Timing Summary" in line:
            return float(content[i + 6].strip().split()[0])
    raise ValueError(f"no timing summary in {rpt_path}")


def main() -> None:
    option = {}
    for key, values in OPTIONS.items():
        if key == "Frequency":
            option[key] = ut.tune(300, values, name=key)
        else:
            option[key] = ut.tune(values[0], values, name=key)

    workdir = tempfile.mkdtemp(prefix="ut_vitis_")
    write_configs(workdir, option)
    flow = os.environ.get("UT_VITIS_FLOW")
    if flow:
        subprocess.run([flow, workdir, json.dumps(option)], check=False,
                       timeout=float(os.environ.get("UT_VITIS_TIMEOUT",
                                                    7200)))
    else:
        subprocess.run([sys.executable,
                        os.path.join(HERE, "mock_flow.py"),
                        workdir, json.dumps(option)], check=True,
                       timeout=600)

    rpt = os.path.join(
        workdir, "reports", "link", "imp",
        "xilinx_u280_xdma_201920_1_bb_locked_timing_summary_"
        "postroute_physopted.rpt")
    xml = os.path.join(workdir, "csynth.xml")
    if os.path.isfile(xml):
        # area/latency covariates into the archive (report.py:122-161)
        ut.vhls(xml, register=True)
    if not os.path.isfile(rpt):
        print("Cannot find vivado timing report...")
        ut.target(math.inf, "min")
        return
    wns = parse_wns(rpt)
    qor = 1000.0 / float(option["Frequency"]) - wns
    ut.target(qor, "min")   # achieved period: lower = faster design
    print(f"freq={option['Frequency']} wns={wns:.3f} "
          f"achieved_period={qor:.3f}ns")


if __name__ == "__main__":
    main()
