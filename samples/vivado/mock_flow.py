"""Deterministic mock Vitis/Vivado implementation flow.

Consumes the config.ini files tune_vitis.py writes, emits (a) the
post-route timing summary report in the real Xilinx text layout the
reference parses (WNS/TNS six lines under "Design Timing Summary",
/root/reference/samples/vivado/tune_vitis.py:126-139) and (b) a Vivado
HLS csynth XML in the schema `ut.vhls` scrapes — so the whole
option -> flow -> report -> extract -> QoR loop runs without licensed
tools.  Point tune_vitis.py's UT_VITIS_FLOW at a real run.sh wrapper to
drive actual builds.

QoR model: WNS degrades with target frequency and improves with
Explore-style directives, enabled phys-opt passes, and per-option
deterministic "luck" — the tradeoff shape of the real implementation
steps (UG904).
"""
import hashlib
import json
import os
import sys


def _luck(opts: dict, salt: str) -> float:
    h = hashlib.sha256(
        (salt + json.dumps(opts, sort_keys=True)).encode()).digest()
    return int.from_bytes(h[:4], "big") / 2 ** 32


DIRECTIVE_GAIN = {
    "Explore": 0.30, "AggressiveExplore": 0.38, "ExploreArea": 0.22,
    "ExploreWithRemap": 0.26, "ExploreWithHoldFix": 0.28,
    "ExploreWithAggressiveHoldFix": 0.27, "AddRemap": 0.15,
    "AddRetime": 0.18, "AlternateReplication": 0.16,
    "AggressiveFanoutOpt": 0.2, "AlternateFlowWithRetiming": 0.24,
    "ExploreSequentialArea": 0.12, "WLDrivenBlockPlacement": 0.2,
    "ExtraNetDelay_high": 0.24, "ExtraNetDelay_low": 0.18,
    "ExtraPostPlacementOpt": 0.26, "ExtraTimingOpt": 0.3,
    "NoTimingRelaxation": 0.22, "MoreGlobalIterations": 0.25,
    "HigherDelayCost": 0.2, "Default": 0.0, "Disabled": -0.1,
    "RuntimeOptimized": -0.15, "Quick": -0.25, "NoBramPowerOpt": 0.05,
}


def run(workdir: str, opts: dict) -> None:
    freq = float(opts.get("Frequency", 300))
    target_period = 1000.0 / freq

    gain = 0.0
    for key, val in opts.items():
        if key.endswith("ARGS.DIRECTIVE"):
            stage_enabled = opts.get(
                key.split(".ARGS")[0] + ".IS_ENABLED", "true") == "true"
            if stage_enabled:
                gain += DIRECTIVE_GAIN.get(val, 0.1)
        elif ".ARGS.MORE." in key and val == "on":
            gain += 0.03
    # placement/routing luck, deterministic in the full config
    gain += 0.25 * _luck(opts, "route")

    # harder to close timing at higher clocks: slack shrinks faster
    # than the period does
    wns = target_period * 0.35 - 2.1 + 0.9 * gain
    tns = min(0.0, wns) * 430.0

    rpt_dir = os.path.join(workdir, "reports", "link", "imp")
    os.makedirs(rpt_dir, exist_ok=True)
    rpt = os.path.join(
        rpt_dir, "xilinx_u280_xdma_201920_1_bb_locked_timing_summary_"
                 "postroute_physopted.rpt")
    with open(rpt, "w") as f:
        f.write(
            "----------------------------------------------------------\n"
            "| Design Timing Summary\n"
            "| ---------------------\n"
            "----------------------------------------------------------\n"
            "\n"
            "    WNS(ns)      TNS(ns)  TNS Failing Endpoints  "
            "TNS Total Endpoints\n"
            "    -------      -------  ---------------------  "
            "-------------------\n"
            f"    {wns:7.3f}    {tns:9.1f}                      0"
            "                12000\n")

    # csynth XML for the ut.vhls covariate path (schema of
    # api/features.py vhls / reference report.py:122-161)
    lut = int(41000 * (1 + 0.2 * gain))
    ff = int(52000 * (1 + 0.1 * gain))
    xml = os.path.join(workdir, "csynth.xml")
    with open(xml, "w") as f:
        f.write(f"""<profile>
  <ReportVersion><Version>2020.1</Version></ReportVersion>
  <UserAssignments>
    <ProductFamily>virtexuplusHBM</ProductFamily>
    <Part>xcu280-fsvh2892-2L-e</Part>
    <TopModelName>krnl</TopModelName>
    <unit>ns</unit>
    <TargetClockPeriod>{target_period:.3f}</TargetClockPeriod>
  </UserAssignments>
  <PerformanceEstimates>
    <SummaryOfTimingAnalysis>
      <EstimatedClockPeriod>{target_period - wns:.3f}</EstimatedClockPeriod>
    </SummaryOfTimingAnalysis>
    <SummaryOfOverallLatency>
      <Best-caseLatency>4200</Best-caseLatency>
      <Worst-caseLatency>5150</Worst-caseLatency>
      <Interval-min>4201</Interval-min>
      <Interval-max>5151</Interval-max>
    </SummaryOfOverallLatency>
  </PerformanceEstimates>
  <AreaEstimates>
    <Resources>
      <BRAM_18K>312</BRAM_18K><DSP48E>224</DSP48E>
      <FF>{ff}</FF><LUT>{lut}</LUT>
    </Resources>
    <AvailableResources>
      <BRAM_18K>4032</BRAM_18K><DSP48E>9024</DSP48E>
      <FF>2607360</FF><LUT>1303680</LUT>
    </AvailableResources>
  </AreaEstimates>
</profile>
""")


if __name__ == "__main__":
    run(sys.argv[1], json.loads(sys.argv[2]))
