"""Halide-schedule-shaped tuning — the space structure of the
reference's halide sample (/root/reference/samples/halide/
halidetuner.py:122-489: a dependency-respecting ScheduleParameter over
pipeline stages plus per-stage tiling/vectorization knobs) over a
deterministic synthetic cost model, so it runs without a Halide
toolchain.

The pipeline: in -> blur_x -> blur_y -> sharpen -> out, with a schedule
ordering constrained by those dependencies (ScheduleParam topologically
normalizes every candidate) and pow2 tile/vector widths per hot stage.
Cost rewards producer-consumer locality (adjacent stages scheduled
close together) and a sweet-spot tile configuration.

    python samples/halide/halide_shaped.py          # library mode
"""
import sys


def main():
    from uptune_tpu.driver.driver import Tuner
    from uptune_tpu.space.params import EnumParam, Pow2Param, ScheduleParam
    from uptune_tpu.space.spec import Space

    stages = ("in", "blur_x", "blur_y", "sharpen", "out")
    deps = (("blur_x", ("in",)),
            ("blur_y", ("blur_x",)),
            ("sharpen", ("blur_y",)),
            ("out", ("sharpen",)))
    space = Space([
        ScheduleParam("order", items=stages, deps=deps),
        Pow2Param("tile_x", 8, 256),
        Pow2Param("tile_y", 8, 256),
        Pow2Param("vec", 4, 32),
        EnumParam("store_at", ("root", "inline", "tile")),
    ])

    def objective(cfgs):
        out = []
        for c in cfgs:
            order = c["order"]
            pos = {s: i for i, s in enumerate(order)}
            # producer-consumer distance = lost locality
            locality = sum(abs(pos[a] - pos[b]) - 1
                           for a, bs in deps for b in bs)
            tile_cost = (abs(pos_log(c["tile_x"]) - 6)      # 64 ideal
                         + abs(pos_log(c["tile_y"]) - 5)    # 32 ideal
                         + abs(pos_log(c["vec"]) - 3))      # 8 ideal
            store = {"root": 1.0, "inline": 0.5, "tile": 0.0}[c["store_at"]]
            out.append(locality * 2.0 + tile_cost + store)
        return out

    def pos_log(v):
        return v.bit_length() - 1

    t = Tuner(space, objective, seed=0)
    res = t.run(test_limit=400)
    t.close()
    print("best schedule:", res.best_config["order"])
    print("tiles:", res.best_config["tile_x"], res.best_config["tile_y"],
          "vec:", res.best_config["vec"],
          "store:", res.best_config["store_at"],
          f"cost={res.best_qor:.2f}")
    # the dependency contract holds for every decoded schedule
    order = res.best_config["order"]
    pos = {s: i for i, s in enumerate(order)}
    assert all(pos[b] < pos[a] for a, bs in deps for b in bs)
    return 0


if __name__ == "__main__":
    sys.exit(main())
