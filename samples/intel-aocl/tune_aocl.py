"""Intel FPGA OpenCL (AOCL) compile tuning — the reference's
intel-aocl sample (/root/reference/samples/intel-aocl/tune_aocl.py:
fitter seed + QSF assignments appended to the generated top.qsf, aoc
recompile, kernel fmax parsed from the Quartus report).

Runs against `mock_flow.py` (deterministic acl_quartus_report.txt in
the real format) by default; set UT_AOCL_FLOW to a wrapper script that
runs `aoc` + Quartus for real builds.  QoR = kernel fmax, maximized.

    ut samples/intel-aocl/tune_aocl.py -pf 2 --test-limit 30
"""
import json
import math
import os
import subprocess
import sys
import tempfile

import uptune_tpu as ut

HERE = os.path.dirname(os.path.realpath(__file__))
DESIGN = "gemm"

option = {
    "seed": ut.tune(1, (1, 100), name="seed"),
    "optimization_technique":
        ut.tune("Balanced", ["Area", "Speed", "Balanced"]),
    "fitter_effort": ut.tune("Auto Fit", ["Standard Fit", "Auto Fit"]),
    "physical_synthesis": ut.tune("Off", ["On", "Off"]),
    "mux_restructure": ut.tune("Auto", ["On", "Off", "Auto"]),
    "fmax_target": ut.tune(240, (200, 400), name="fmax_target"),
}

workdir = tempfile.mkdtemp(prefix="ut_aocl_")
# QSF assignments appended to the HLS-generated project, like the
# reference's config() writes into top.qsf / afu_opencl_kernel.qsf
with open(os.path.join(workdir, "top.qsf"), "w") as f:
    f.write(f"set_global_assignment -name SEED {option['seed']}\n")
    for k in ("optimization_technique", "fitter_effort",
              "physical_synthesis", "mux_restructure"):
        f.write(f'set_global_assignment -name "{k}" "{option[k]}"\n')

flow = os.environ.get("UT_AOCL_FLOW")
if flow:
    subprocess.run([flow, workdir, json.dumps(option)], check=False,
                   timeout=float(os.environ.get("UT_AOCL_TIMEOUT",
                                                20 * 3600)))
else:
    subprocess.run([sys.executable, os.path.join(HERE, "mock_flow.py"),
                    workdir, json.dumps(option)], check=True, timeout=600)

rpt = os.path.join(workdir, DESIGN, "acl_quartus_report.txt")
fmax = None
if os.path.isfile(rpt):
    import re
    with open(rpt) as f:
        m = re.search(r"Kernel fmax: (\d+\.?\d*)", f.read())
    if m:
        fmax = float(m.group(1))
if fmax is None:
    ut.target(-math.inf, "max")
else:
    ut.target(fmax, "max")
    print(f"seed={option['seed']} fmax={fmax:.1f}MHz")
