"""Deterministic mock AOCL/Quartus compile: writes the
acl_quartus_report.txt summary (the 'Actual clock freq' line the AOCL
flow reports) shaped by seed luck, effort options, and the requested
fmax target — diminishing returns past the design's intrinsic limit."""
import hashlib
import json
import os
import sys


def run(workdir: str, opts: dict) -> None:
    seed = int(opts.get("seed", 1))
    target = float(opts.get("fmax_target", 240))
    luck_bytes = hashlib.sha256(
        json.dumps(opts, sort_keys=True).encode()).digest()
    luck = int.from_bytes(luck_bytes[:4], "big") / 2 ** 32
    seed_luck = ((seed * 2654435761) % 997) / 997.0

    base = 255.0
    base += {"Speed": 18.0, "Balanced": 8.0, "Area": 0.0}[
        opts["optimization_technique"]]
    base += 10.0 if opts["physical_synthesis"] == "On" else 0.0
    base += 6.0 if opts["fitter_effort"] == "Standard Fit" else 0.0
    base += 22.0 * seed_luck + 6.0 * luck
    # over-constraining the clock hurts: the fitter gives up slack
    fmax = min(base, target + 25.0) - max(0.0, target - base) * 0.3

    d = os.path.join(workdir, "gemm")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "acl_quartus_report.txt"), "w") as f:
        f.write("ALUTs: 188,244\nRegisters: 313,799\n"
                "Logic utilization: 247,610 / 427,200 ( 58 % )\n"
                "I/O pins: 289\nDSP blocks: 146\n"
                "Memory bits: 26,321,777\nRAM blocks: 2,434\n"
                f"Actual clock freq: {fmax:.0f}\n"
                f"Kernel fmax: {fmax:.2f}\n")


if __name__ == "__main__":
    run(sys.argv[1], json.loads(sys.argv[2]))
