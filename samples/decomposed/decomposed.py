"""Two-stage pipeline demo (the reference's
`samples/decomposed/decompsed.py:1-14` shape): two `ut.target` calls act
as stage breakpoints, so the CLI auto-decouples tuning — stage 1 trials
replay stage 0's best config."""
import uptune_tpu as ut

# stage 0: pick a quantization scale
scale = ut.tune(8, (1, 32), name="scale")
err0 = abs(scale - 24) / 24.0
ut.target(float(err0), "min")

# stage 1: pick a schedule given the chosen scale
unroll = ut.tune(1, [1, 2, 4, 8, 16], name="unroll")
cost = err0 + abs(unroll * scale - 96) / 96.0
ut.target(float(cost), "min")
