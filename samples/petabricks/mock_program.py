"""Mock PetaBricks autotunable binary: the interface shape of a real
PetaBricks program (config exemplar + `--config=<file>` runs printing a
timing element) with a deterministic algorithmic-choice cost model —
sort with cutoff-switched algorithms, a blocking knob, and a selector
between strategies, the canonical PetaBricks tutorial knobs.

  mock_program.py --print-config          # exemplar: name kind spec...
  mock_program.py --config=cfg.json -n N  # prints <timing time="S"/>
"""
import json
import math
import sys

KNOBS = [
    # name, kind, spec
    ("sort_cutoff", "log_int", {"lo": 1, "hi": 4096, "default": 64}),
    ("block_size", "log_int", {"lo": 1, "hi": 512, "default": 8}),
    ("parallel_split", "int", {"lo": 1, "hi": 16, "default": 2}),
    ("strategy", "selector",
     {"choices": ["insertion", "quick", "merge", "radix"],
      "default": "quick"}),
    ("use_prefetch", "switch", {"n": 2, "default": 0}),
]


def cost(cfg: dict, n: int) -> float:
    """Deterministic runtime model with a real optimum: radix+large
    blocks wins at big n, insertion+small cutoff at small n."""
    cutoff = int(cfg["sort_cutoff"])
    block = int(cfg["block_size"])
    split = int(cfg["parallel_split"])
    strat = cfg["strategy"]
    pref = int(cfg["use_prefetch"])

    base = {"insertion": 0.004 * n * max(1, n / max(cutoff, 1)) * 1e-3,
            "quick": 1.4e-6 * n * math.log2(max(n, 2)),
            "merge": 1.6e-6 * n * math.log2(max(n, 2)),
            "radix": 9e-6 * n}[strat]
    base *= 1.0 + 0.35 * abs(math.log2(block) - 5) / 5
    base *= 1.0 + 0.2 * abs(split - 8) / 8
    base *= 0.92 if pref else 1.0
    # mis-set cutoff hurts the recursive strategies
    if strat in ("quick", "merge"):
        base *= 1.0 + 0.3 * abs(math.log2(max(cutoff, 1)) - 6) / 6
    return base


def main() -> int:
    if "--print-config" in sys.argv:
        for name, kind, spec in KNOBS:
            print(json.dumps({"name": name, "kind": kind, **spec}))
        return 0
    cfg_path = next(a.split("=", 1)[1] for a in sys.argv
                    if a.startswith("--config="))
    n = int(sys.argv[sys.argv.index("-n") + 1])
    with open(cfg_path) as f:
        cfg = json.load(f)
    print(f'<timing time="{cost(cfg, n):.6f}"/>')
    return 0


if __name__ == "__main__":
    sys.exit(main())
