"""PetaBricks autotuner bridge — the reference's petabricks sample
(/root/reference/samples/petabricks/pbtuner.py): read a program's
config exemplar, build the search space (Integer/LogInteger/Switch/
Selector parameters), tune by running `program --config=<file> -n N`
and parsing the `<timing time=.../>` output, write the best config.

Library-mode (ask/tell) rather than `ut` CLI, like the reference uses
the OpenTuner MeasurementInterface directly.  Works out of the box
against mock_program.py; point it at any binary speaking the same
protocol.

    python samples/petabricks/pbtuner.py [program] [-n 100000]
        [--test-limit 120] [--output best_cfg.json]
"""
import argparse
import json
import math
import os
import re
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# library-mode script: unlike the `ut` CLI (which calls force_cpu
# itself), plain python must drop the axon TPU-tunnel backend before the
# first jax op or a wedged tunnel hangs the run during backend init
from uptune_tpu.utils.platform_guard import force_cpu  # noqa: E402

force_cpu(1)

_TIMING = re.compile(r'<timing\s+time="([0-9.eE+-]+)"')


def build_space(exemplar_lines):
    from uptune_tpu.space.params import (IntParam, LogIntParam,
                                         SelectorParam, SwitchParam)
    from uptune_tpu.space.spec import Space

    specs = []
    for line in exemplar_lines:
        k = json.loads(line)
        if k["kind"] == "int":
            specs.append(IntParam(k["name"], k["lo"], k["hi"]))
        elif k["kind"] == "log_int":
            specs.append(LogIntParam(k["name"], k["lo"], k["hi"]))
        elif k["kind"] == "switch":
            specs.append(SwitchParam(k["name"], k["n"]))
        elif k["kind"] == "selector":
            specs.append(SelectorParam(k["name"],
                                       choices=tuple(k["choices"])))
        else:
            raise ValueError(f"unknown knob kind {k['kind']!r}")
    return Space(specs)


def run_once(program, cfg: dict, n: int, timeout: float) -> float:
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(cfg, f)
        path = f.name
    try:
        out = subprocess.run(
            [*program, f"--config={path}", "-n", str(n)],
            capture_output=True, text=True, timeout=timeout)
        if out.returncode != 0:
            return math.inf
        m = _TIMING.search(out.stdout)
        return float(m.group(1)) if m else math.inf
    except subprocess.TimeoutExpired:
        return math.inf
    finally:
        os.unlink(path)


def decode(space, cfg: dict) -> dict:
    """Normalize selector values to choices (Space.to_configs already
    decodes positions to choices; raw positions appear only if a caller
    hands this function an encoded config)."""
    from uptune_tpu.space.params import SelectorParam
    out = dict(cfg)
    for spec in space.specs:
        if isinstance(spec, SelectorParam):
            v = cfg[spec.name]
            out[spec.name] = (v if v in spec.choices
                              else spec.choice_of(v))
    return out


def main() -> int:
    here = os.path.dirname(os.path.abspath(__file__))
    ap = argparse.ArgumentParser()
    ap.add_argument("program", nargs="*",
                    default=[sys.executable,
                             os.path.join(here, "mock_program.py")])
    ap.add_argument("-n", type=int, default=100000)
    ap.add_argument("--test-limit", type=int, default=120)
    ap.add_argument("--run-timeout", type=float, default=30.0)
    ap.add_argument("--output", default="best_cfg.json")
    args = ap.parse_args()
    program = args.program

    exemplar = subprocess.run(
        [*program, "--print-config"], capture_output=True, text=True,
        timeout=60, check=True).stdout.splitlines()
    space = build_space([ln for ln in exemplar if ln.strip()])

    from uptune_tpu.driver.driver import Tuner

    def objective(cfgs):
        return [run_once(program, decode(space, c), args.n,
                         args.run_timeout) for c in cfgs]

    t = Tuner(space, objective, seed=0)
    res = t.run(test_limit=args.test_limit)
    t.close()
    best = decode(space, res.best_config)
    with open(args.output, "w") as f:
        json.dump(best, f, indent=1)
    print(json.dumps({"best_config": best, "best_time": res.best_qor,
                      "evals": res.evals}))
    return 0


if __name__ == "__main__":
    main()
