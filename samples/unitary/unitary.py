"""Quantum-control unitary synthesis in SU(2) — the reference's unitary
sample (/root/reference/samples/unitary/unitary.py: choose, from a finite
control set, an operator sequence whose product approximates a goal
unitary in minimal time, within an admissible error).

Unlike most EDA samples this one is fully computable here: the payload
is 2x2 complex matrix products.  Each of SEQ_LEN slots picks a control
(one of two rotation generators, or idle) and a duration; QoR is the
infidelity to the goal plus a small total-time penalty, so the tuner
must hit the target AND do it fast — the reference's "optimal time"
objective.

    ut samples/unitary/unitary.py -pf 2 --test-limit 200
"""
import cmath
import math

import uptune_tpu as ut

SEQ_LEN = 8

# control set: rotations about x and y at fixed Rabi rate, plus idle
# (free evolution is a z-rotation at the detuning rate)
def rx(theta):
    c, s = math.cos(theta / 2), math.sin(theta / 2)
    return ((c, -1j * s), (-1j * s, c))


def ry(theta):
    c, s = math.cos(theta / 2), math.sin(theta / 2)
    return ((c, -s), (s, c))


def rz(theta):
    return ((cmath.exp(-0.5j * theta), 0), (0, cmath.exp(0.5j * theta)))


def mm(a, b):
    return tuple(tuple(sum(a[i][k] * b[k][j] for k in range(2))
                       for j in range(2)) for i in range(2))


# goal: the reference's 'fixed' Ugoal shape — a specific SU(2) element
# reachable only by composing both generators
U_GOAL = mm(rx(1.9), mm(ry(0.7), rz(1.3)))

u = ((1, 0), (0, 1))
total_t = 0.0
for i in range(SEQ_LEN):
    ctrl = ut.tune("idle", ["x", "y", "idle"], name=f"ctrl{i}")
    dt = ut.tune(0.0, (0.0, math.pi), name=f"dt{i}")
    if ctrl == "x":
        u = mm(rx(dt), u)
        total_t += dt
    elif ctrl == "y":
        u = mm(ry(dt), u)
        total_t += dt
    else:
        u = mm(rz(0.15 * dt), u)  # idle: slow free precession
        total_t += dt

# gauge-invariant fidelity |tr(U† Ugoal)| / 2
tr = sum(u[j][i].conjugate() * U_GOAL[j][i] for i in range(2)
         for j in range(2))
infidelity = 1.0 - abs(tr) / 2.0
qor = infidelity + 0.01 * total_t

ut.target(qor, "min")
print(f"infidelity={infidelity:.4f} time={total_t:.2f} qor={qor:.4f}")
