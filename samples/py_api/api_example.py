"""Library-mode usage: drive the batched Tuner directly, both with an
in-process objective and externally paced via ask()/tell() — the
counterpart of the reference's TuningRunManager example
(/root/reference/samples/py_api/api_example.py and
opentuner/api.py:18-53 get_next_desired_result/report_result).

Run:  python samples/py_api/api_example.py
"""
import sys


def main():
    from uptune_tpu.driver.driver import Tuner
    from uptune_tpu.space.params import EnumParam, FloatParam, IntParam
    from uptune_tpu.space.spec import Space

    space = Space([
        FloatParam("alpha", 0.0, 1.0),
        IntParam("block", 1, 64),
        EnumParam("opt", ("O0", "O1", "O2", "O3")),
    ])

    def objective(cfgs):
        return [
            (c["alpha"] - 0.8) ** 2 * 10
            + (c["block"] - 32) ** 2 / 64.0
            + {"O0": 2.0, "O1": 1.0, "O2": 0.5, "O3": 0.0}[c["opt"]]
            for c in cfgs
        ]

    # 1. in-process loop (measurement-interface style)
    tuner = Tuner(space, objective, seed=0)
    res = tuner.run(test_limit=300)
    tuner.close()
    print("in-process best:", res.best_config, f"qor={res.best_qor:.4f}")

    # 2. ask/tell: evaluation paced by external machinery
    tuner = Tuner(space, seed=1)
    for _ in range(10):
        trials = tuner.ask(min_trials=8)
        for tr in trials:
            tuner.tell(tr, objective([tr.config])[0])
    res = tuner.result()
    tuner.close()
    print("ask/tell best:  ", res.best_config, f"qor={res.best_qor:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
