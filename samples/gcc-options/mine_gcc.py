"""Mine the installed g++'s real optimization space.

The reference mines its gcc space at tune time (/root/reference/samples/
gcc-options/tune_gcc.py:100-163): `-f...` flags from `--help=optimizers`
validity-checked one by one, numeric `--param`s with defaults parsed out
of gcc's params.def source.  Modern gcc (>= 10) prints every param's
range and default directly (`g++ -Q --help=params` lines like
`--param=asan-globals=<0,1>  1`), so this miner needs no compiler source
tree: flags come from --help=optimizers, params from -Q --help=params
(only those with an explicit <min,max> range and an integer default),
and each surviving option is proven to compile a trivial program before
it enters the space.

Results are cached as JSON next to this file keyed by `g++ --version`,
so the one-time ~1-2 min validity sweep is shared by every evaluation
sandbox (the worker pool symlink-farms the sample dir; realpath lands
here).  Flags are tuned as on/off/default tri-states exactly like the
reference (tune_gcc.py:189-197 cfg_to_flags: on -> -fX, off -> -fno-X,
default -> omitted).
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import tempfile
from typing import Dict, List, Optional, Tuple

_DIR = os.path.dirname(os.path.realpath(__file__))
_CACHE = os.path.join(_DIR, ".gcc_space_cache.json")

_PARAM_LINE = re.compile(
    r"^\s+--param=([a-zA-Z0-9-]+)=<(-?\d+),(\d+)>\s+(-?\d+)\s*$")
_FLAG_LINE = re.compile(r"^  (-f[a-z0-9-]+) ", re.MULTILINE)


def _cc_version(cc: str = "g++") -> str:
    out = subprocess.run([cc, "--version"], capture_output=True,
                         text=True, timeout=30)
    return out.stdout.splitlines()[0].strip() if out.stdout else "unknown"


def _flag_works(cc: str, opts: List[str]) -> bool:
    """True when `cc -O2 <opts>` compiles a trivial program cleanly
    (tune_gcc.py:60-74 check_if_flag_works)."""
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "t.cpp")
        with open(src, "w") as f:
            f.write("int main() { return 0; }\n")
        try:
            r = subprocess.run(
                [cc, "-O2", *opts, src, "-o", os.path.join(d, "t.bin")],
                capture_output=True, timeout=60)
        except subprocess.TimeoutExpired:
            return False
    return r.returncode == 0


def mine(cc: str = "g++", use_cache: bool = True,
         max_flags: Optional[int] = None,
         max_params: Optional[int] = None) -> Dict[str, object]:
    """-> {'version', 'flags': [...], 'params': {name: [lo, hi, dflt]}}

    Concurrency: on a cold cache, N parallel sandboxes (`ut ... -pf N`
    imports this in every worker) would each run the full ~1-2 min
    flag-validity sweep.  An exclusive flock serializes them: one worker
    mines while the rest block on the lock, then read the cache it
    wrote (ADVICE r3)."""
    version = _cc_version(cc)

    def _read_cache():
        if use_cache and os.path.exists(_CACHE):
            try:
                with open(_CACHE) as f:
                    cached = json.load(f)
                if cached.get("version") == version:
                    return cached
            except (json.JSONDecodeError, OSError):
                pass
        return None

    cached = _read_cache()
    if cached is not None:
        return cached
    if use_cache:
        import fcntl
        with open(_CACHE + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                # whoever held the lock before us probably mined already
                cached = _read_cache()
                if cached is not None:
                    return cached
                return _mine_uncached(cc, version, use_cache,
                                      max_flags, max_params)
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)
    return _mine_uncached(cc, version, use_cache, max_flags, max_params)


def _mine_uncached(cc, version, use_cache, max_flags, max_params):
    out = subprocess.run([cc, "--help=optimizers"], capture_output=True,
                         text=True, timeout=60)
    candidates = sorted(set(_FLAG_LINE.findall(out.stdout)))
    if max_flags:
        candidates = candidates[:max_flags]
    flags = [fl for fl in candidates if _flag_works(cc, [fl])
             and _flag_works(cc, [_off(fl)])]

    out = subprocess.run([cc, "-Q", "--help=params"], capture_output=True,
                         text=True, timeout=60)
    params: Dict[str, Tuple[int, int, int]] = {}
    for line in out.stdout.splitlines():
        m = _PARAM_LINE.match(line)
        if not m:
            continue
        name, lo, hi, dflt = (m.group(1), int(m.group(2)),
                              int(m.group(3)), int(m.group(4)))
        if lo >= hi:
            continue
        dflt = min(max(dflt, lo), hi)
        params[name] = (lo, hi, dflt)
    if max_params:
        params = dict(sorted(params.items())[:max_params])
    params = {n: v for n, v in params.items()
              if _flag_works(cc, [f"--param={n}={v[2]}"])}

    mined = {"version": version, "flags": flags,
             "params": {n: list(v) for n, v in params.items()}}
    if use_cache:
        tmp = _CACHE + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(mined, f)
        os.replace(tmp, _CACHE)   # atomic vs concurrent sandboxes
    return mined


def _off(flag: str) -> str:
    return "-fno-" + flag[2:]


def build_and_time(cc_args: List[str], src: str,
                   expected: Optional[bytes] = None, runs: int = 3,
                   cc: str = "g++", compile_timeout: float = 120.0,
                   run_timeout: float = 60.0) -> float:
    """Compile `src` with `cc_args`, run it `runs` times, return the
    best wall time — or +inf on compile failure, crash, timeout, or
    (when `expected` is given) output that differs from the anchor.

    The output gate is load-bearing: without it the tuner 'wins' with
    ABI-breaking miscompiles (observed: -fpack-struct makes the qsort
    payload print 0 in 3.5ms instead of its checksum in 385ms).  Shared
    by the `ut` sample and the benchreport gcc-real problem so the gate
    semantics can't drift apart."""
    import math
    import time as _time

    exe = tempfile.NamedTemporaryFile(suffix=".bin", delete=False).name
    try:
        try:
            r = subprocess.run([cc, *cc_args, src, "-o", exe],
                               capture_output=True,
                               timeout=compile_timeout)
        except subprocess.TimeoutExpired:
            return math.inf
        if r.returncode != 0:
            return math.inf
        best = math.inf
        for _ in range(runs):
            t0 = _time.perf_counter()
            try:
                out = subprocess.run([exe], capture_output=True,
                                     timeout=run_timeout, check=True)
            except (subprocess.TimeoutExpired,
                    subprocess.CalledProcessError, OSError):
                return math.inf
            best = min(best, _time.perf_counter() - t0)
            if expected is not None and out.stdout != expected:
                return math.inf
        return best
    finally:
        if os.path.exists(exe):
            os.unlink(exe)


def anchor_output(src: str, extra: List[str] = (), cc: str = "g++",
                  use_cache: bool = True) -> bytes:
    """Reference stdout of a plain -O2 build of `src` — the output every
    tuned build must reproduce.  Cached next to this file keyed by a
    digest of (compiler version, payload source, extra build args), so
    editing the payload, switching compilers, or passing different
    `extra` defines invalidates the cache instead of silently validating
    trials against a wrong anchor (a payload whose output depends on a
    tuned -D would otherwise bake the first trial's define into the
    cached anchor)."""
    import hashlib

    with open(src, "rb") as f:
        payload = f.read()
    digest = hashlib.sha256(
        _cc_version(cc).encode() + b"\0" + payload + b"\0"
        + " ".join(extra).encode()).hexdigest()[:12]
    stem = os.path.splitext(os.path.basename(src))[0]
    cache = os.path.join(_DIR, f".anchor_{stem}_{digest}.bin")
    if use_cache and os.path.exists(cache):
        with open(cache, "rb") as f:
            return f.read()
    with tempfile.TemporaryDirectory() as d:
        exe = os.path.join(d, "anchor.bin")
        subprocess.run([cc, "-O2", *extra, src, "-o", exe],
                       capture_output=True, timeout=120, check=True)
        out = subprocess.run([exe], capture_output=True, timeout=60,
                             check=True).stdout
    if use_cache:
        tmp = cache + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(out)
        os.replace(tmp, cache)
    return out


def config_to_cmd(cfg: Dict[str, object], mined: Dict[str, object]
                  ) -> List[str]:
    """Config dict -> g++ argument list (cfg_to_flags,
    tune_gcc.py:180-197)."""
    args = [str(cfg["olevel"])]
    for fl in mined["flags"]:
        v = cfg.get(fl, "default")
        if v == "on":
            args.append(fl)
        elif v == "off":
            args.append(_off(fl))
    for name in mined["params"]:
        if name in cfg:
            args.append(f"--param={name}={int(cfg[name])}")
    return args


def build_space(mined: Dict[str, object]):
    """Mined description -> uptune_tpu Space (for library-mode use, e.g.
    the benchreport real-gcc row; the `ut` CLI sample declares the same
    space via ut.tune calls instead)."""
    from uptune_tpu.space.params import EnumParam, IntParam
    from uptune_tpu.space.spec import Space

    specs = [EnumParam("olevel", ("-O0", "-O1", "-O2", "-O3"))]
    for fl in mined["flags"]:
        specs.append(EnumParam(fl, ("default", "on", "off")))
    for name, (lo, hi, _d) in sorted(mined["params"].items()):
        specs.append(IntParam(name, int(lo), int(hi)))
    return Space(specs)
