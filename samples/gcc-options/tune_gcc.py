"""Tune the REAL mined g++ optimization space on a real payload — the
reference's flagship workload (/root/reference/samples/gcc-options/
tune_gcc.py): -O level, every working `-f` optimizer flag as an
on/off/default tri-state, and every ranged numeric `--param`, mined from
the installed compiler by mine_gcc (first run sweeps flag validity for
~30s, then cached).  ~330 parameters on g++ 12.

    ut samples/gcc-options/tune_gcc.py -pf 4 --test-limit 60 \
        --runtime-limit 120

Payload selection (UT_GCC_PAYLOAD): `mmm` (default) = the tutorial's
blocked matmul, with BLOCK_SIZE tuned alongside the compiler space;
`qsort` = sort/arithmetic benchmark.  QoR = best-of-3 wall time of the
compiled binary (seconds); failed compiles report +inf.

Budget-constrained recipes (r5, measured at 30 matched seeds per
BENCHREPORT.md — on this space a default `--learning-models gp` run
automatically applies the bandit-arbitrated surrogate plane and
measured 0.86x the bandit baseline with a perfect solve rate):

    # warm-start from a previous run's best (or any known-good flags)
    ut samples/gcc-options/tune_gcc.py --test-limit 80 \
        --seed-configuration best_flags.json

    # transfer per-flag sensitivity mined from ANOTHER payload's
    # archive over this same space (off by default — measured
    # payload-specific; see BENCHREPORT "Cross-payload screening")
    ut samples/gcc-options/tune_gcc.py --learning-models gp \
        --surrogate-screen other_payload.archive.jsonl \
        --surrogate-screen-mode soft
"""
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.realpath(__file__)))
import mine_gcc  # noqa: E402

import uptune_tpu as ut  # noqa: E402

MINED = mine_gcc.mine()

olevel = ut.tune("-O2", ["-O0", "-O1", "-O2", "-O3"], name="olevel")
cfg = {"olevel": olevel}
for fl in MINED["flags"]:
    cfg[fl] = ut.tune("default", ["default", "on", "off"], name=fl)
for name, (lo, hi, dflt) in sorted(MINED["params"].items()):
    cfg[name] = ut.tune(int(dflt), (int(lo), int(hi)), name=name)

here = os.path.dirname(os.path.realpath(__file__))
payload = os.environ.get("UT_GCC_PAYLOAD", "mmm")
if payload == "mmm":
    src = os.path.join(here, "mmm_block.cpp")
    block = ut.tune(16, (4, 128), name="block_size")
    extra = [f"-DBLOCK_SIZE={block}"]
else:
    src = os.path.join(here, "payload_qsort.cpp")
    extra = []

# correctness gate: a tuned config only counts if the payload still
# prints the -O2 anchor's output (ABI-breaking flag combos -- e.g.
# -fpack-struct on libstdc++ code -- otherwise "win" by miscompiling);
# the anchor is cached keyed by (compiler version, payload source) so a
# payload edit invalidates it instead of failing every trial
want = mine_gcc.anchor_output(src, extra)
best = mine_gcc.build_and_time(
    [*mine_gcc.config_to_cmd(cfg, MINED), *extra], src, expected=want)
ut.target(best, "min")
if math.isfinite(best):
    n_on = sum(1 for fl in MINED["flags"] if cfg[fl] != "default")
    print(f"{olevel} touched_flags={n_on} t={best:.4f}s")
else:
    print(f"{olevel} FAILED (compile error, crash, or wrong output)")
