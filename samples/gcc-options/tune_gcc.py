"""Tune real g++ flags + block size on a blocked matmul — the shape of
the reference's gcc-options sample (/root/reference/samples/gcc-options/
tune_gcc.py: -O level, on/off optimizer flags, numeric params) on the
tutorial's mmm_block payload, small enough to run anywhere g++ exists.

    ut samples/gcc-options/tune_gcc.py -pf 2 --test-limit 30 \
        --runtime-limit 60

QoR = best-of-3 wall time of the compiled binary (seconds); failed
compiles report +inf and count as failures.
"""
import math
import os
import subprocess
import tempfile
import time

import uptune_tpu as ut

olevel = ut.tune("-O2", ["-O0", "-O1", "-O2", "-O3"], name="olevel")
FLAGS = ("-funroll-loops", "-ftree-vectorize", "-ffast-math",
         "-fomit-frame-pointer", "-finline-functions")
enabled = [ut.tune(False, name=f) for f in FLAGS]
block = ut.tune(16, (4, 128), name="block_size")

src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "mmm_block.cpp")
exe = tempfile.NamedTemporaryFile(suffix=".bin", delete=False).name
cmd = (["g++", olevel, f"-DBLOCK_SIZE={block}"]
       + [f for f, on in zip(FLAGS, enabled) if on]
       + [src, "-o", exe])

try:
    cc = subprocess.run(cmd, capture_output=True, timeout=120)
    if cc.returncode != 0:
        ut.target(math.inf, "min")      # compile failure
    else:
        best = math.inf
        for _ in range(3):
            t0 = time.perf_counter()
            subprocess.run([exe], capture_output=True, timeout=60,
                           check=True)
            best = min(best, time.perf_counter() - t0)
        ut.target(best, "min")
        print(f"{olevel} block={block} "
              f"flags={[f for f, on in zip(FLAGS, enabled) if on]} "
              f"t={best:.4f}s")
finally:
    if os.path.exists(exe):
        os.unlink(exe)
