// Second gcc-options payload: sorting + integer arithmetic, the same
// role as the reference's extra gcc-options apps (tsp_ga, raytracer —
// /root/reference/samples/gcc-options/src/) but self-contained and
// seconds-scale.  Deterministic (fixed LCG seed); prints a checksum so
// the optimizer cannot dead-code the work away.
#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <vector>

static inline uint32_t lcg(uint32_t &s) {
    s = s * 1664525u + 1013904223u;
    return s;
}

int main() {
    uint32_t seed = 12345u;
    uint64_t checksum = 0;
    for (int round = 0; round < 24; ++round) {
        std::vector<uint32_t> v(120000);
        for (auto &x : v) x = lcg(seed);
        std::sort(v.begin(), v.end());
        // branchy binary-search workload over the sorted data
        for (int q = 0; q < 60000; ++q) {
            uint32_t key = lcg(seed);
            auto it = std::lower_bound(v.begin(), v.end(), key);
            if (it != v.end()) checksum += *it >> 7;
        }
        // integer kernel with data-dependent flow
        for (size_t i = 1; i + 1 < v.size(); i += 3) {
            uint32_t a = v[i - 1], b = v[i], c = v[i + 1];
            checksum += (a > b ? a - b : b - a) ^ (c * 2654435761u >> 5);
        }
    }
    std::printf("%llu\n", (unsigned long long)checksum);
    return 0;
}
