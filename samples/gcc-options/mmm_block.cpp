// Blocked matrix multiply — the tuning payload of the reference's
// getting-started tutorial (/root/reference/samples/tutorials/
// gettingstarted.md: tune BLOCK_SIZE + gcc flags on mmm_block.cpp).
#include <cstdio>

#ifndef BLOCK_SIZE
#define BLOCK_SIZE 16
#endif
#define N 420

static double A[N][N], B[N][N], C[N][N];

int main() {
  for (int i = 0; i < N; ++i)
    for (int j = 0; j < N; ++j) {
      A[i][j] = (i + j) % 7;
      B[i][j] = (i * j) % 13;
      C[i][j] = 0.0;
    }
  for (int ii = 0; ii < N; ii += BLOCK_SIZE)
    for (int kk = 0; kk < N; kk += BLOCK_SIZE)
      for (int jj = 0; jj < N; jj += BLOCK_SIZE)
        for (int i = ii; i < (ii + BLOCK_SIZE < N ? ii + BLOCK_SIZE : N);
             ++i)
          for (int k = kk; k < (kk + BLOCK_SIZE < N ? kk + BLOCK_SIZE : N);
               ++k)
            for (int j = jj;
                 j < (jj + BLOCK_SIZE < N ? jj + BLOCK_SIZE : N); ++j)
              C[i][j] += A[i][k] * B[k][j];
  double sum = 0.0;
  for (int i = 0; i < N; ++i) sum += C[i][i];
  std::printf("checksum %.1f\n", sum);
  return 0;
}
