// Third gcc-options payload: integer 2-D stencil + reduction — the
// SIMD-bound profile (vectorization, unrolling, ivopts flag territory),
// complementing payload_qsort.cpp (branchy sort/search) and
// mmm_block.cpp (cache-blocked matmul); fills the role of the
// reference's loop-kernel gcc-options apps
// (/root/reference/samples/gcc-options/src/).  All-integer arithmetic
// so the output-equivalence gate stays exact under any legal transform
// (a float stencil would change results under re-association and
// poison the validity gate).  All arithmetic is UNSIGNED: int32 sums
// here overflow by round 3, which would be UB — and the mined space
// contains -ftrapv/-fwrapv, so overflow semantics genuinely vary by
// config (r4 review) — while uint32 wraps identically under every
// legal transform.  Deterministic; prints a checksum so the work
// cannot be dead-coded away.
#include <cstdint>
#include <cstdio>
#include <vector>

static inline uint32_t lcg(uint32_t &s) {
    s = s * 1664525u + 1013904223u;
    return s;
}

int main() {
    const int W = 1024, H = 768, ROUNDS = 240;
    std::vector<uint32_t> a(W * H), b(W * H);
    uint32_t seed = 987654321u;
    for (auto &x : a) x = lcg(seed) & 0xffffu;
    uint64_t checksum = 0;
    for (int r = 0; r < ROUNDS; ++r) {
        // 5-point integer stencil with shift/multiply mixing
        for (int y = 1; y + 1 < H; ++y) {
            const uint32_t *up = &a[(y - 1) * W], *mid = &a[y * W],
                           *dn = &a[(y + 1) * W];
            uint32_t *out = &b[y * W];
            for (int x = 1; x + 1 < W; ++x) {
                uint32_t v = 3u * mid[x] + up[x] + dn[x] + mid[x - 1]
                             + mid[x + 1];
                out[x] = (v >> 1) ^ (v << 3);
            }
        }
        // mixing sweep (vectorizable per-row reductions)
        uint64_t rowsum = 0;
        for (int y = 1; y + 1 < H; ++y) {
            const uint32_t *row = &b[y * W];
            for (int x = 1; x + 1 < W; ++x)
                rowsum += (row[x] * 2654435761u) >> 9;
        }
        checksum += rowsum;
        a.swap(b);
    }
    std::printf("%llu\n", (unsigned long long)checksum);
    return 0;
}
