"""Travelling salesman over a permutation parameter — the reference's
tsp sample (/root/reference/samples/tsp/tsp.py:1-19): tune the city
tour, evaluate the closed-tour length on a fixed distance matrix.

    ut samples/tsp/tsp.py -pf 2 --test-limit 300
"""
import math

import uptune_tpu as ut

N = 12
# deterministic city ring with noise: optimum is (near) the ring order
CITIES = [(math.cos(2 * math.pi * i / N) + 0.013 * ((i * 7919) % 10),
           math.sin(2 * math.pi * i / N) + 0.013 * ((i * 104729) % 10))
          for i in range(N)]

tour = ut.tune(list(range(N)), list(range(N)), name="tour")

length = 0.0
for a, b in zip(tour, tour[1:] + tour[:1]):
    (x1, y1), (x2, y2) = CITIES[a], CITIES[b]
    length += math.hypot(x2 - x1, y2 - y1)

ut.target(length, "min")
print("tour length:", length)
